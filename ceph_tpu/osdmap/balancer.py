"""The upmap balancer — calc_pg_upmaps on the batched mapper.

Re-derives the reference's upmap optimizer
(``OSDMap::calc_pg_upmaps``, src/osd/OSDMap.cc:4618-5115, plus
``try_pg_upmap`` :4575 and ``CrushWrapper::get_rule_weight_osd_map``,
src/crush/CrushWrapper.cc:2397): compute every OSD's PG-count deviation
from its weight-proportional target, then iteratively move PGs from
overfull to underfull OSDs by appending ``pg_upmap_items`` exception
pairs, accepting only changes that strictly reduce the deviation
stddev.

TPU-first shape: the full-cluster "map every PG" pass that dominates
the reference's runtime (OSDMap.cc:4642, via thread-pooled
OSDMapMapping) is ONE batched launch per pool here
(``PoolMapper.map_all``); the iterative search mutates host-side
tallies exactly like the reference (no remapping inside the loop — the
candidate evaluation is pure bookkeeping plus scalar
``try_remap_rule`` calls).

Divergence note: where the reference shuffles candidate lists with a
``random_device`` in aggressive mode, this uses a seeded RNG so runs
are reproducible; set ``seed`` for different explorations.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

from ..crush.constants import CRUSH_ITEM_NONE
from ..crush.wrapper import CrushWrapper
from .osdmap import OSDMap, PgPool

PgId = Tuple[int, int]  # (pool_id, ps)


def get_rule_weight_osd_map(wrapper: CrushWrapper,
                            ruleno: int) -> Dict[int, float]:
    """osd -> normalized share of the rule's tree weight
    (CrushWrapper.cc:2397): per TAKE, sum device weights under the
    take root, normalize, merge."""
    pmap: Dict[int, float] = {}
    rule = wrapper.crush.rules.get(ruleno)
    if rule is None:
        raise KeyError(f"no rule {ruleno}")
    for root in wrapper.find_takes_by_rule(ruleno):
        m: Dict[int, float] = {}
        total = 0.0
        if root >= 0:
            m[root] = 1.0
            total = 1.0
        else:
            for leaf in wrapper.get_leaves(root):
                p = wrapper.get_immediate_parent_id(leaf)
                # weight of the leaf within its parent bucket
                b = wrapper.get_bucket(p) if p is not None else None
                w = (b.item_weight_at(b.items.index(leaf)) / 0x10000
                     if b is not None else 0.0)
                m[leaf] = m.get(leaf, 0.0) + w
                total += w
        if total:
            for osd, w in m.items():
                pmap[osd] = pmap.get(osd, 0.0) + w / total
    return pmap


def pg_to_raw_upmap(m: OSDMap, pool_id: int,
                    ps: int) -> Tuple[List[int], List[int]]:
    """OSDMap.cc:2635: (raw crush mapping, raw with upmaps applied)."""
    pool = m.pools[pool_id]
    raw, _pps = m._pg_to_raw_osds(pool_id, pool, ps)
    pgid = (pool_id, pool.raw_pg_to_ps(ps))
    upmapped = m._apply_upmap(pool, pgid, list(raw))
    return raw, upmapped


def try_pg_upmap(m: OSDMap, wrapper: CrushWrapper, pool_id: int,
                 ps: int, overfull: Set[int], underfull: List[int],
                 more_underfull: List[int]
                 ) -> Optional[Tuple[List[int], List[int]]]:
    """OSDMap.cc:4575: propose an alternative mapping for one PG via
    CrushWrapper.try_remap_rule; None when nothing changes."""
    pool = m.pools[pool_id]
    if pool.crush_rule not in m.crush.rules:
        return None
    _raw, orig = pg_to_raw_upmap(m, pool_id, ps)
    if not any(o in overfull for o in orig):
        return None
    out = wrapper.try_remap_rule(pool.crush_rule, pool.size, overfull,
                                 underfull, more_underfull, orig)
    if out == orig or len(out) != len(orig):
        return None
    return orig, out


def build_pgs_by_osd(m: OSDMap,
                     only_pools: Optional[Set[int]] = None,
                     use_batched: bool = False,
                     mappers: Optional[Dict[int, object]] = None,
                     mesh=None) -> Dict[int, Set[PgId]]:
    """Map every PG of every (selected) pool and tally per OSD — the
    full-cluster remap (OSDMap.cc:4633-4646).  ``use_batched`` routes
    through the fused batched pipeline (one TPU launch per pool);
    otherwise the scalar spec.

    ``mappers`` is a caller-owned ``{pool_id: PoolMapper}`` cache: the
    closed balancer loop re-sweeps the same pools every round, so a
    cached mapper only relowers its exception tables
    (``refresh_tables``) instead of rebuilding the compiled program.
    ``mesh`` shards each pool's PG axis across the device mesh (the
    PlacementPlane distribution shape from the multichip plane)."""
    pgs_by_osd: Dict[int, Set[PgId]] = {}
    for pool_id, pool in m.pools.items():
        if only_pools and pool_id not in only_pools:
            continue
        if use_batched:
            import numpy as np

            from .pipeline_jax import PoolMapper

            if mappers is not None:
                pm = mappers.get(pool_id)
                if pm is None or pm.m is not m:
                    pm = PoolMapper(m, pool_id, mesh)
                    mappers[pool_id] = pm
                else:
                    pm.refresh_tables()
            else:
                pm = PoolMapper(m, pool_id, mesh)
            out = pm.map_all()
            up = np.asarray(out["up"])
            ulen = np.asarray(out["up_len"])
            for ps in range(pool.pg_num):
                pgid = (pool_id, ps)
                for o in up[ps, :ulen[ps]]:
                    if o != CRUSH_ITEM_NONE and o >= 0:
                        pgs_by_osd.setdefault(int(o), set()).add(pgid)
        else:
            for ps in range(pool.pg_num):
                up, _p, _a, _ap = m.pg_to_up_acting_osds(pool_id, ps)
                for o in up:
                    if o != CRUSH_ITEM_NONE:
                        pgs_by_osd.setdefault(o, set()).add(
                            (pool_id, ps))
    return pgs_by_osd


def target_osd_weights(m: OSDMap, wrapper: CrushWrapper,
                       only_pools: Optional[Set[int]] = None
                       ) -> Tuple[Dict[int, float], float, int]:
    """The per-OSD weight-proportional targets every deviation sweep
    measures against (OSDMap.cc:4646-4700): each selected pool's rule
    tree contributes its normalized per-OSD share scaled by the
    reweight column.  Returns (osd_weight, weight_total, total_pgs)."""
    total_pgs = 0
    osd_weight: Dict[int, float] = {}
    osd_weight_total = 0.0
    for pool_id, pool in m.pools.items():
        if only_pools and pool_id not in only_pools:
            continue
        total_pgs += pool.size * pool.pg_num
        pmap = get_rule_weight_osd_map(wrapper, pool.crush_rule)
        for osd, share in pmap.items():
            if osd >= len(m.osd_weight):
                continue
            adjusted = (m.osd_weight[osd] / 0x10000) * share
            if adjusted == 0:
                continue
            osd_weight[osd] = osd_weight.get(osd, 0.0) + adjusted
            osd_weight_total += adjusted
    return osd_weight, osd_weight_total, total_pgs


def _deviations(pgs_by_osd: Dict[int, Set[PgId]],
                osd_weight: Dict[int, float], pgs_per_weight: float):
    dev: Dict[int, float] = {}
    stddev = 0.0
    max_dev = 0.0
    for osd, pgs in pgs_by_osd.items():
        if osd not in osd_weight:
            # an upmap-pair endpoint outside the weighted tree (e.g. a
            # since-zeroed osd re-added by a drop-pair simulation); the
            # reference ceph_asserts here — skipping is the safe
            # equivalent (it has no target to deviate from)
            continue
        target = osd_weight[osd] * pgs_per_weight
        d = len(pgs) - target
        dev[osd] = d
        stddev += d * d
        max_dev = max(max_dev, abs(d))
    return dev, stddev, max_dev


def calc_pg_upmaps(m: OSDMap,
                   max_deviation: int = 5,
                   max_iterations: int = 10,
                   only_pools: Optional[Set[int]] = None,
                   wrapper: Optional[CrushWrapper] = None,
                   use_batched: bool = False,
                   aggressive: bool = True,
                   local_fallback_retries: int = 100,
                   seed: int = 0,
                   mappers: Optional[Dict[int, object]] = None,
                   mesh=None) -> int:
    """OSDMap.cc:4618.  Mutates ``m.pg_upmap_items`` in place; returns
    the number of table changes (additions + removals)."""
    if max_deviation < 1:
        max_deviation = 1
    if wrapper is None:
        wrapper = CrushWrapper(m.crush)
    rng = random.Random(seed)

    # -- the one full-cluster remap (the TPU launch) -------------------
    pgs_by_osd = build_pgs_by_osd(m, only_pools, use_batched,
                                  mappers=mappers, mesh=mesh)

    osd_weight, osd_weight_total, total_pgs = target_osd_weights(
        m, wrapper, only_pools)
    for osd in osd_weight:
        pgs_by_osd.setdefault(osd, set())
    # drop tallies for osds outside the weight map (down/out devices)
    pgs_by_osd = {o: p for o, p in pgs_by_osd.items()
                  if o in osd_weight}
    if osd_weight_total == 0 or total_pgs == 0:
        return 0
    pgs_per_weight = total_pgs / osd_weight_total

    osd_deviation, stddev, cur_max = _deviations(
        pgs_by_osd, osd_weight, pgs_per_weight)
    if cur_max <= max_deviation:
        return 0

    num_changed = 0
    skip_overfull = False
    it = max_iterations
    while it > 0:
        it -= 1
        by_dev_desc = sorted(osd_deviation,
                             key=lambda o: (-osd_deviation[o], o))
        by_dev_asc = sorted(osd_deviation,
                            key=lambda o: (osd_deviation[o], o))
        overfull = {o for o in by_dev_desc
                    if osd_deviation[o] > max_deviation}
        more_overfull = {o for o in by_dev_desc
                         if 0 < osd_deviation[o] <= max_deviation}
        underfull = [o for o in by_dev_asc
                     if osd_deviation[o] < -max_deviation]
        more_underfull = [o for o in by_dev_asc
                          if -max_deviation <= osd_deviation[o] < 0]
        if not underfull and not overfull:
            break
        using_more_overfull = False
        if not overfull and underfull:
            overfull = more_overfull
            using_more_overfull = True
        if not overfull:
            break

        to_skip: Set[PgId] = set()
        local_fallback_retried = 0
        applied = False
        while True:  # retry: label
            to_unmap: Set[PgId] = set()
            to_upmap: Dict[PgId, List[Tuple[int, int]]] = {}
            temp = {o: set(p) for o, p in pgs_by_osd.items()}
            found = _search_overfull(
                m, wrapper, by_dev_desc, osd_deviation, osd_weight,
                pgs_per_weight, overfull, underfull, more_underfull,
                using_more_overfull, max_deviation, skip_overfull,
                to_skip, temp, to_unmap, to_upmap, only_pools,
                aggressive, rng)
            if not found:
                found = _search_underfull(
                    m, by_dev_asc, osd_deviation, underfull,
                    max_deviation, to_skip, temp, to_unmap, to_upmap,
                    only_pools, aggressive, rng)
            if not found:
                if not aggressive:
                    return num_changed
                if not skip_overfull:
                    return num_changed
                skip_overfull = False
                break  # continue outer loop
            # test_change (OSDMap.cc:5031)
            t_dev, new_stddev, cur_max = _deviations(
                temp, osd_weight, pgs_per_weight)
            if new_stddev >= stddev:
                if not aggressive:
                    return num_changed
                local_fallback_retried += 1
                if local_fallback_retried >= local_fallback_retries:
                    skip_overfull = not skip_overfull
                    break  # continue outer loop
                to_skip |= to_unmap | set(to_upmap)
                continue  # retry
            # apply
            stddev = new_stddev
            pgs_by_osd = temp
            osd_deviation = t_dev
            for pgid in to_unmap:
                del m.pg_upmap_items[pgid]
                num_changed += 1
            for pgid, items in to_upmap.items():
                m.pg_upmap_items[pgid] = items
                num_changed += 1
            applied = True
            break
        if applied and cur_max <= max_deviation:
            break
    return num_changed


def _search_overfull(m, wrapper, by_dev_desc, osd_deviation, osd_weight,
                     pgs_per_weight, overfull, underfull,
                     more_underfull, using_more_overfull, max_deviation,
                     skip_overfull, to_skip, temp, to_unmap, to_upmap,
                     only_pools, aggressive, rng) -> bool:
    """OSDMap.cc:4771-4936: first change that helps an overfull osd."""
    for osd in by_dev_desc:
        if skip_overfull and underfull:
            break
        deviation = osd_deviation[osd]
        if deviation < 0:
            break
        if not using_more_overfull and deviation <= max_deviation:
            break
        pgs = [p for p in sorted(temp.get(osd, ()))
               if p not in to_skip]
        if aggressive:
            rng.shuffle(pgs)
        # 1) drop an existing remapping pair that lands on this osd
        for pgid in pgs:
            items = m.pg_upmap_items.get(pgid)
            if items is None:
                continue
            new_items = [q for q in items if q[1] != osd]
            if len(new_items) == len(items):
                continue
            for q in items:
                if q[1] == osd:
                    temp[q[1]].discard(pgid)
                    temp.setdefault(q[0], set()).add(pgid)
            if not new_items:
                to_unmap.add(pgid)
            else:
                to_upmap[pgid] = new_items
            return True
        # 2) append a new remapping pair
        for pgid in pgs:
            if pgid in m.pg_upmap:
                continue  # balancer leaves explicit pg_upmap alone
            pool_id, ps = pgid
            pool = m.pools[pool_id]
            existing: Set[int] = set()
            new_items: List[Tuple[int, int]] = []
            items = m.pg_upmap_items.get(pgid)
            if items is not None:
                if len(items) >= pool.size:
                    continue
                new_items = list(items)
                for a, b in items:
                    existing.add(a)
                    existing.add(b)
            res = try_pg_upmap(m, wrapper, pool_id, ps, overfull,
                               underfull, more_underfull)
            if res is None:
                continue
            orig, out = res
            pos, max_dev = -1, 0.0
            for i in range(len(out)):
                if orig[i] == out[i]:
                    continue
                if orig[i] in existing or out[i] in existing:
                    continue
                d = osd_deviation.get(orig[i], 0.0)
                if d > max_dev:
                    max_dev, pos = d, i
            if pos < 0:
                continue
            frm, to = orig[pos], out[pos]
            temp.setdefault(frm, set()).discard(pgid)
            temp.setdefault(to, set()).add(pgid)
            new_items.append((frm, to))
            to_upmap[pgid] = new_items
            return True
    return False


# ---------------------------------------------------------------------------
# crush-compat mode (balancer module.py do_crush_compat, :964-1120)
# ---------------------------------------------------------------------------

def distribution_score(m: OSDMap, osd_weight: Dict[int, float],
                       only_pools: Optional[Set[int]] = None,
                       pgs_by_osd: Optional[Dict[int, Set[PgId]]] = None
                       ) -> float:
    """Imbalance score in [0, 1), 0 = perfect (module.py:181-224
    spirit: weight-share-weighted erf of relative deviation)."""
    import math

    if pgs_by_osd is None:
        pgs_by_osd = build_pgs_by_osd(m, only_pools)
    total = sum(len(p) for p in pgs_by_osd.values())
    wsum = sum(osd_weight.values())
    if not total or not wsum:
        return 0.0
    score = 0.0
    for osd, share in osd_weight.items():
        share /= wsum
        if share <= 0:
            continue
        avg = total * share
        actual = len(pgs_by_osd.get(osd, ()))
        dev = abs(actual - avg) / avg if avg else 0.0
        score += share * math.erf(dev / math.sqrt(2.0))
    return score


def weight_set_to_choose_args(wrapper: CrushWrapper,
                              ws: Dict[int, float]):
    """Lower per-device weight-set values (crush-weight units) to a
    hierarchical choose_args set: every bucket's weight_set row is the
    accumulated subtree value — the compat weight-set shape the
    reference stores (CrushWrapper choose_args, crush.h:263-284)."""
    from ..crush.map import ChooseArg, ChooseArgMap

    def subtree(item: int) -> float:
        if item >= 0:
            return max(0.0, ws.get(item, 0.0))
        return sum(subtree(c) for c in wrapper.get_bucket(item).items)

    cam = ChooseArgMap()
    for idx, b in wrapper.crush.buckets.items():
        if b.id in wrapper._shadow_ids:
            continue
        row = [int(round(subtree(c) * 0x10000)) for c in b.items]
        cam[idx] = ChooseArg(ids=None, weight_set=[row])
    return cam


def do_crush_compat(m: OSDMap,
                    wrapper: Optional[CrushWrapper] = None,
                    max_iterations: int = 25,
                    step: float = 0.5,
                    max_misplaced: float = 0.10,
                    only_pools: Optional[Set[int]] = None,
                    min_score: float = 0.0,
                    seed: int = 0):
    """The balancer's crush-compat mode: iteratively adjust a
    choose_args weight set (NOT the real hierarchy weights) so actual
    PG counts converge to crush-weight-proportional targets, accepting
    steps that reduce the score within the misplacement budget.
    Returns (score_before, score_after, choose_args) and installs the
    winning set as ``m.crush.choose_args['compat']``."""
    if wrapper is None:
        wrapper = CrushWrapper(m.crush)
    if not (0.0 < step < 1.0):
        raise ValueError("step must be in (0, 1)")

    # targets from the rule trees; weight shares per osd
    osd_weight: Dict[int, float] = {}
    total_pgs = 0
    for pool_id, pool in m.pools.items():
        if only_pools and pool_id not in only_pools:
            continue
        total_pgs += pool.size * pool.pg_num
        for osd, share in get_rule_weight_osd_map(
                wrapper, pool.crush_rule).items():
            if osd < len(m.osd_weight) and m.osd_weight[osd] > 0:
                osd_weight[osd] = osd_weight.get(osd, 0.0) + share
    if not osd_weight or not total_pgs:
        return 0.0, 0.0, None

    def mapping_of(cam) -> Dict[int, Set[PgId]]:
        saved = dict(m.crush.choose_args)
        if cam is not None:
            m.crush.choose_args["compat"] = cam
            for pool_id in m.pools:
                m.crush.choose_args.setdefault(
                    pool_id, m.crush.choose_args["compat"])
        try:
            return build_pgs_by_osd(m, only_pools)
        finally:
            m.crush.choose_args = saved

    base_map = mapping_of(None)
    base_pairs = {(o, pg) for o, pgs in base_map.items() for pg in pgs}
    score0 = distribution_score(m, osd_weight, only_pools, base_map)
    if score0 <= min_score:
        return score0, score0, None

    wsum = sum(osd_weight.values())
    # initial weight set = the real crush weights (compat semantics)
    ws: Dict[int, float] = {}
    for osd in osd_weight:
        try:
            ws[osd] = wrapper.get_item_weight(osd) / 0x10000
        except KeyError:
            ws[osd] = 1.0

    best_ws = dict(ws)
    best_map = base_map
    best_score = score0
    cur_step = step
    for _ in range(max_iterations):
        nxt = dict(best_ws)
        actual_total = sum(len(p) for p in best_map.values())
        total_ws = sum(nxt.values())
        for osd, share in osd_weight.items():
            target = actual_total * (share / wsum)
            actual = len(best_map.get(osd, ()))
            weight = nxt[osd]
            if actual > 0:
                calc = (target / actual) * weight
            else:
                # empty osd: aim at its fair share of the current
                # weight-set mass (PG counts are not weight units)
                calc = (share / wsum) * total_ws
            nxt[osd] = weight * (1.0 - cur_step) + calc * cur_step
        cam = weight_set_to_choose_args(wrapper, nxt)
        new_map = mapping_of(cam)
        new_pairs = {(o, pg) for o, pgs in new_map.items()
                     for pg in pgs}
        misplaced = (len(base_pairs - new_pairs)
                     / max(1, len(base_pairs)))
        new_score = distribution_score(m, osd_weight, only_pools,
                                       new_map)
        if misplaced > max_misplaced or new_score >= best_score:
            cur_step /= 2.0
            if cur_step < 0.01:
                break
            continue
        best_ws, best_map, best_score = nxt, new_map, new_score
        if best_score <= min_score:
            break

    if best_score >= score0:
        return score0, score0, None
    cam = weight_set_to_choose_args(wrapper, best_ws)
    m.crush.choose_args["compat"] = cam
    for pool_id in m.pools:
        if not only_pools or pool_id in only_pools:
            m.crush.choose_args[pool_id] = cam
    return score0, best_score, cam


def _search_underfull(m, by_dev_asc, osd_deviation, underfull,
                      max_deviation, to_skip, temp, to_unmap, to_upmap,
                      only_pools, aggressive, rng) -> bool:
    """OSDMap.cc:4940-5010: cancel remapping pairs that drain an
    underfull osd."""
    for osd in by_dev_asc:
        if osd not in underfull:
            break
        deviation = osd_deviation[osd]
        if abs(deviation) < max_deviation:
            break
        candidates = [(pgid, items)
                      for pgid, items in sorted(m.pg_upmap_items.items())
                      if pgid not in to_skip
                      and (not only_pools or pgid[0] in only_pools)]
        if aggressive:
            rng.shuffle(candidates)
        for pgid, items in candidates:
            new_items = [q for q in items if q[0] != osd]
            if len(new_items) == len(items):
                continue
            for q in items:
                if q[0] == osd:
                    temp.setdefault(q[1], set()).discard(pgid)
                    temp.setdefault(q[0], set()).add(pgid)
            if not new_items:
                to_unmap.add(pgid)
            else:
                to_upmap[pgid] = new_items
            return True
    return False
