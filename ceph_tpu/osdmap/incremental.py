"""OSDMap::Incremental — epoch deltas instead of full maps.

The role of src/osd/OSDMap.h:372-675 + OSDMap::apply_incremental
(OSDMap.cc): each epoch change travels as a small delta (state XORs,
weight changes, pool creations, upmap adds/removals, pg_temp edits,
an optional full crush replacement) that any holder of epoch N applies
to reach N+1; a gap means "fetch a full map and catch up" — the
MonClient subscription contract that keeps map distribution O(change),
not O(cluster).

Deltas serialize through the versioned envelope
(common/encoding.py), mirroring the reference's versioned
Incremental::encode.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common.encoding import Versioned
from .osdmap import OSDMap, PgPool

PgId = Tuple[int, int]


def _kv(d):
    return [[list(k), v] for k, v in sorted(d.items())]


def _unkv(rows):
    return {tuple(k): v for k, v in rows}


@dataclass
class Incremental(Versioned):
    """The delta from ``epoch - 1`` to ``epoch``."""

    # v2: added pg_upmap / primary_temp / pool-deletion deltas.  They
    # affect placement, so a v1 reader cannot safely skip them —
    # COMPAT_V rises with STRUCT_V and old followers refuse the delta
    # (and fall back to a full-map fetch) instead of silently diverging.
    STRUCT_V = 2
    COMPAT_V = 2

    epoch: int = 0
    new_max_osd: Optional[int] = None
    new_pools: Dict[int, dict] = field(default_factory=dict)
    old_pools: List[int] = field(default_factory=list)
    new_state: Dict[int, int] = field(default_factory=dict)  # XOR
    new_weight: Dict[int, int] = field(default_factory=dict)
    new_primary_affinity: Dict[int, int] = field(default_factory=dict)
    new_pg_upmap: Dict[PgId, List[int]] = field(default_factory=dict)
    old_pg_upmap: List[PgId] = field(default_factory=list)
    new_pg_upmap_items: Dict[PgId, List[Tuple[int, int]]] = \
        field(default_factory=dict)
    old_pg_upmap_items: List[PgId] = field(default_factory=list)
    new_pg_temp: Dict[PgId, List[int]] = field(default_factory=dict)
    # -1 removes the entry (OSDMap.h:397 new_primary_temp semantics)
    new_primary_temp: Dict[PgId, int] = field(default_factory=dict)
    new_crush: Optional[dict] = None  # full crush swap (rare)

    @classmethod
    def upgrade(cls, writer_v: int, data: dict) -> dict:
        """Migrate archived v1 deltas (pre pg_upmap/primary_temp/
        pool-deletion) forward: the v2-added tables default to empty.
        A v1 WRITER could not have populated them, so an explicit
        empty is exactly its intent — the per-version decode branch
        of the reference's Incremental::decode."""
        if writer_v < 2:
            data = dict(data)
            for key in ("new_pg_upmap", "old_pg_upmap",
                        "new_primary_temp", "old_pools"):
                data.setdefault(key, [])
        return data

    def empty(self) -> bool:
        return not (self.new_max_osd is not None or self.new_pools
                    or self.old_pools
                    or self.new_state or self.new_weight
                    or self.new_primary_affinity
                    or self.new_pg_upmap or self.old_pg_upmap
                    or self.new_pg_upmap_items
                    or self.old_pg_upmap_items or self.new_pg_temp
                    or self.new_primary_temp
                    or self.new_crush)

    # -- wire form ----------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "new_max_osd": self.new_max_osd,
            "new_pools": {str(k): v for k, v in self.new_pools.items()},
            "old_pools": list(self.old_pools),
            "new_state": {str(k): v for k, v in self.new_state.items()},
            "new_weight": {str(k): v
                           for k, v in self.new_weight.items()},
            "new_primary_affinity": {
                str(k): v
                for k, v in self.new_primary_affinity.items()},
            "new_pg_upmap": _kv(self.new_pg_upmap),
            "old_pg_upmap": [list(p) for p in self.old_pg_upmap],
            "new_pg_upmap_items": _kv(self.new_pg_upmap_items),
            "old_pg_upmap_items": [list(p)
                                   for p in self.old_pg_upmap_items],
            "new_pg_temp": _kv(self.new_pg_temp),
            "new_primary_temp": _kv(self.new_primary_temp),
            "new_crush": self.new_crush,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Incremental":
        inc = cls(epoch=d["epoch"])
        inc.new_max_osd = d.get("new_max_osd")
        inc.new_pools = {int(k): v
                         for k, v in d.get("new_pools", {}).items()}
        inc.old_pools = [int(p) for p in d.get("old_pools", [])]
        inc.new_state = {int(k): v
                         for k, v in d.get("new_state", {}).items()}
        inc.new_weight = {int(k): v
                          for k, v in d.get("new_weight", {}).items()}
        inc.new_primary_affinity = {
            int(k): v
            for k, v in d.get("new_primary_affinity", {}).items()}
        inc.new_pg_upmap = {k: list(v) for k, v in
                            _unkv(d.get("new_pg_upmap", [])).items()}
        inc.old_pg_upmap = [tuple(p) for p in d.get("old_pg_upmap", [])]
        inc.new_pg_upmap_items = {
            k: [tuple(p) for p in v]
            for k, v in _unkv(d.get("new_pg_upmap_items", [])).items()}
        inc.old_pg_upmap_items = [tuple(p) for p in
                                  d.get("old_pg_upmap_items", [])]
        inc.new_pg_temp = _unkv(d.get("new_pg_temp", []))
        inc.new_primary_temp = _unkv(d.get("new_primary_temp", []))
        inc.new_crush = d.get("new_crush")
        return inc


def diff_maps(old: OSDMap, new: OSDMap) -> Incremental:
    """Build the delta old -> new (the OSDMonitor's pending_inc role,
    derived by comparison so every mutation path is covered)."""
    inc = Incremental(epoch=new.epoch)
    if new.max_osd != old.max_osd:
        inc.new_max_osd = new.max_osd
    for pool_id, pool in new.pools.items():
        if pool_id not in old.pools or \
                old.pools[pool_id].to_dict() != pool.to_dict():
            inc.new_pools[pool_id] = pool.to_dict()
    for pool_id in old.pools:
        if pool_id not in new.pools:
            inc.old_pools.append(pool_id)
    # only osds that EXIST in the new map carry deltas: a shrink
    # truncates the arrays via new_max_osd, so deltas above it would
    # index out of bounds at apply time
    for osd in range(new.max_osd):
        os_ = old.osd_state[osd] if osd < old.max_osd else 0
        ns = new.osd_state[osd]
        if os_ != ns:
            inc.new_state[osd] = os_ ^ ns
        ow = old.osd_weight[osd] if osd < old.max_osd else 0
        nw = new.osd_weight[osd]
        if ow != nw:
            inc.new_weight[osd] = nw
    if new.osd_primary_affinity != old.osd_primary_affinity:
        from .osdmap import DEFAULT_PRIMARY_AFFINITY

        for osd in range(new.max_osd):
            # None lists mean "all default": a reset-to-default
            # transition must still emit deltas for every osd whose old
            # affinity was non-default, or followers keep stale values
            na = new.osd_primary_affinity[osd] \
                if new.osd_primary_affinity else DEFAULT_PRIMARY_AFFINITY
            oa = old.osd_primary_affinity[osd] \
                if old.osd_primary_affinity and \
                osd < len(old.osd_primary_affinity) \
                else DEFAULT_PRIMARY_AFFINITY
            if na != oa:
                inc.new_primary_affinity[osd] = na
    for pgid, raw in new.pg_upmap.items():
        if old.pg_upmap.get(pgid) != raw:
            inc.new_pg_upmap[pgid] = list(raw)
    for pgid in old.pg_upmap:
        if pgid not in new.pg_upmap:
            inc.old_pg_upmap.append(pgid)
    for pgid, items in new.pg_upmap_items.items():
        if old.pg_upmap_items.get(pgid) != items:
            inc.new_pg_upmap_items[pgid] = list(items)
    for pgid in old.pg_upmap_items:
        if pgid not in new.pg_upmap_items:
            inc.old_pg_upmap_items.append(pgid)
    for pgid, temp in new.pg_temp.items():
        if old.pg_temp.get(pgid) != temp:
            inc.new_pg_temp[pgid] = list(temp)
    for pgid in old.pg_temp:
        if pgid not in new.pg_temp:
            inc.new_pg_temp[pgid] = []  # [] removes (OSDMap.h:389)
    for pgid, osd in new.primary_temp.items():
        if old.primary_temp.get(pgid) != osd:
            inc.new_primary_temp[pgid] = osd
    for pgid in old.primary_temp:
        if pgid not in new.primary_temp:
            inc.new_primary_temp[pgid] = -1  # -1 removes
    if old.crush.to_dict() != new.crush.to_dict():
        inc.new_crush = new.crush.to_dict()
    return inc


def apply_incremental(m: OSDMap, inc: Incremental) -> None:
    """OSDMap::apply_incremental (OSDMap.cc): epoch must be
    contiguous."""
    if inc.epoch != m.epoch + 1:
        raise ValueError(
            f"incremental {inc.epoch} does not follow {m.epoch}")
    if inc.new_crush is not None:
        from ..crush.map import CrushMap

        m.crush = CrushMap.from_dict(inc.new_crush)
    if inc.new_max_osd is not None:
        m.set_max_osd(inc.new_max_osd)
    for pool_id, pd in inc.new_pools.items():
        m.pools[pool_id] = PgPool.from_dict(pd)
    for pool_id in inc.old_pools:
        m.pools.pop(pool_id, None)
    for osd, xor in inc.new_state.items():
        m.osd_state[osd] ^= xor  # XORed onto previous (OSDMap.h:387)
    for osd, w in inc.new_weight.items():
        m.osd_weight[osd] = w
    for osd, aff in inc.new_primary_affinity.items():
        m.set_primary_affinity(osd, aff)
    for pgid, raw in inc.new_pg_upmap.items():
        m.pg_upmap[pgid] = list(raw)
    for pgid in inc.old_pg_upmap:
        m.pg_upmap.pop(pgid, None)
    for pgid, items in inc.new_pg_upmap_items.items():
        m.pg_upmap_items[pgid] = [tuple(p) for p in items]
    for pgid in inc.old_pg_upmap_items:
        m.pg_upmap_items.pop(pgid, None)
    for pgid, temp in inc.new_pg_temp.items():
        if temp:
            m.pg_temp[pgid] = list(temp)
        else:
            m.pg_temp.pop(pgid, None)
    for pgid, osd in inc.new_primary_temp.items():
        if osd >= 0:
            m.primary_temp[pgid] = osd
        else:
            m.primary_temp.pop(pgid, None)
    m.epoch = inc.epoch
