"""Binary OSDMap / CrushMap encoding — the map half of encoding.h.

The reference distributes maps as versioned binary encodes
(CrushWrapper::encode, src/crush/CrushWrapper.h:1550; OSDMap::encode,
src/osd/OSDMap.cc) — never as text.  This module gives the framework
the same property over ``common.bincode`` envelopes: the 10k-OSD full
map is ~200 KB raw (vs ~3 MB of JSON), so full-map distribution needs
no wire compression.  The JSON dict forms (``to_dict``) remain the
tool/debug surface, exactly as the reference keeps its formatter
dumps beside the binary encode.

Array-heavy fields (bucket items/weights, osd state/weight vectors)
travel as little-endian 32-bit array blobs via numpy — one memcpy
each way, no per-element Python loop.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..common.bincode import DecodeError, Decoder, Encoder
from ..crush.map import (Bucket, ChooseArg, ChooseArgMap, CrushMap,
                         Rule, RuleStep, Tunables)
from .osdmap import OSDMap, PgPool


def _arr(enc: Encoder, xs, dtype="<i4") -> None:
    enc.blob(np.asarray(list(xs), dtype).tobytes())


def _unarr(dec: Decoder, dtype="<i4") -> List[int]:
    blob = dec.blob()
    try:
        return np.frombuffer(blob, dtype).tolist()
    except ValueError as e:
        # a tampered length word leaves a ragged array blob; that is
        # a protocol error, not a numpy usage error
        raise DecodeError(f"{dec.struct_name}: bad array blob: {e}")


# -- crush ------------------------------------------------------------------

def encode_crush(m: CrushMap, enc: Encoder) -> None:
    enc.start(1, 1)
    t = m.tunables
    for v in (t.choose_local_tries, t.choose_local_fallback_tries,
              t.choose_total_tries, t.chooseleaf_descend_once,
              t.chooseleaf_vary_r, t.chooseleaf_stable):
        enc.u32(v)
    enc.u32(m.max_devices)
    enc.u32(len(m.buckets))
    for idx in sorted(m.buckets):
        b = m.buckets[idx]
        enc.u32(idx).u8(b.alg).u8(b.hash).u32(b.type).u32(b.weight)
        _arr(enc, b.items)
        enc.u32(b.item_weight)
        _arr(enc, b.item_weights, "<u4")
        _arr(enc, b.sum_weights, "<u4")
        _arr(enc, b.node_weights, "<u4")
        enc.u32(b.num_nodes)
        _arr(enc, b.straws, "<u4")
    enc.u32(len(m.rules))
    for rno in sorted(m.rules):
        r = m.rules[rno]
        enc.u32(rno).u32(r.type)
        flat = []
        for s in r.steps:
            flat += [s.op, s.arg1, s.arg2]
        _arr(enc, flat)
    enc.u32(len(m.choose_args))
    for key in sorted(m.choose_args, key=str):
        cam = m.choose_args[key]
        enc.str_(str(key))
        enc.u32(len(cam))
        for bi in sorted(cam):
            ca = cam[bi]
            enc.u32(bi)
            enc.u8(1 if ca.ids is not None else 0)
            if ca.ids is not None:
                _arr(enc, ca.ids)
            enc.u8(1 if ca.weight_set is not None else 0)
            if ca.weight_set is not None:
                enc.u32(len(ca.weight_set))
                for pos in ca.weight_set:
                    _arr(enc, pos, "<u4")
    enc.finish()


def decode_crush(dec: Decoder) -> CrushMap:
    dec.start(1, struct_name="osdmap.crush")
    tun = Tunables(*(dec.u32() for _ in range(6)))
    m = CrushMap(tunables=tun)
    max_devices = dec.u32()
    for _ in range(dec.u32()):
        idx = dec.u32()
        alg, hsh, type_, weight = dec.u8(), dec.u8(), dec.u32(), \
            dec.u32()
        items = _unarr(dec)
        b = Bucket(id=-1 - idx, alg=alg, hash=hsh, type=type_,
                   weight=weight, items=items,
                   item_weight=dec.u32(),
                   item_weights=_unarr(dec, "<u4"),
                   sum_weights=_unarr(dec, "<u4"),
                   node_weights=_unarr(dec, "<u4"),
                   num_nodes=dec.u32(),
                   straws=_unarr(dec, "<u4"))
        m.add_bucket(b)
    for _ in range(dec.u32()):
        rno, rtype = dec.u32(), dec.u32()
        flat = _unarr(dec)
        steps = [RuleStep(*flat[i:i + 3])
                 for i in range(0, len(flat), 3)]
        m.add_rule(Rule(steps=steps, type=rtype), rno)
    for _ in range(dec.u32()):
        key = dec.str_()
        cam = ChooseArgMap()
        for _ in range(dec.u32()):
            bi = dec.u32()
            ids = _unarr(dec) if dec.u8() else None
            ws = None
            if dec.u8():
                ws = [_unarr(dec, "<u4") for _ in range(dec.u32())]
            cam[bi] = ChooseArg(ids=ids, weight_set=ws)
        # mirror from_dict's key convention: pool ids arrive as str
        m.choose_args[int(key) if key.lstrip("-").isdigit()
                      else key] = cam
    m.max_devices = max(m.max_devices, max_devices)
    dec.finish()
    return m


# -- osdmap -----------------------------------------------------------------

def encode_osdmap(m: OSDMap, enc: Encoder) -> None:
    enc.start(1, 1)
    enc.u32(m.epoch).u32(m.max_osd)
    _arr(enc, m.osd_state, "<u4")
    _arr(enc, m.osd_weight, "<u4")
    enc.u8(1 if m.osd_primary_affinity is not None else 0)
    if m.osd_primary_affinity is not None:
        _arr(enc, m.osd_primary_affinity, "<u4")
    enc.u32(len(m.pools))
    for pid in sorted(m.pools):
        p = m.pools[pid]
        enc.u32(pid).u8(p.pool_type).u32(p.size).u32(p.min_size)
        enc.u32(p.pg_num).u32(p.pgp_num).u32(p.crush_rule)
        enc.u32(p.flags)
        enc.str_(p.erasure_code_profile)
    for table in (m.pg_upmap, m.pg_temp):
        enc.u32(len(table))
        for (pool, ps) in sorted(table):
            enc.u32(pool).u32(ps)
            _arr(enc, table[(pool, ps)])
    enc.u32(len(m.pg_upmap_items))
    for (pool, ps) in sorted(m.pg_upmap_items):
        enc.u32(pool).u32(ps)
        flat = []
        for a, b in m.pg_upmap_items[(pool, ps)]:
            flat += [a, b]
        _arr(enc, flat)
    enc.u32(len(m.primary_temp))
    for (pool, ps) in sorted(m.primary_temp):
        enc.u32(pool).u32(ps)
        enc.i64(m.primary_temp[(pool, ps)])
    encode_crush(m.crush, enc)
    enc.finish()


def decode_osdmap(dec: Decoder) -> OSDMap:
    dec.start(1, struct_name="osdmap.full")
    epoch, max_osd = dec.u32(), dec.u32()
    osd_state = _unarr(dec, "<u4")
    osd_weight = _unarr(dec, "<u4")
    affinity = _unarr(dec, "<u4") if dec.u8() else None
    pools = {}
    for _ in range(dec.u32()):
        pid = dec.u32()
        pools[pid] = PgPool(
            pool_type=dec.u8(), size=dec.u32(), min_size=dec.u32(),
            pg_num=dec.u32(), pgp_num=dec.u32(),
            crush_rule=dec.u32(), flags=dec.u32(),
            erasure_code_profile=dec.str_())
    pg_upmap = {}
    pg_temp = {}
    for table in (pg_upmap, pg_temp):
        for _ in range(dec.u32()):
            pool, ps = dec.u32(), dec.u32()
            table[(pool, ps)] = _unarr(dec)
    pg_upmap_items = {}
    for _ in range(dec.u32()):
        pool, ps = dec.u32(), dec.u32()
        flat = _unarr(dec)
        pg_upmap_items[(pool, ps)] = [
            (flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]
    primary_temp = {}
    for _ in range(dec.u32()):
        pool, ps = dec.u32(), dec.u32()
        primary_temp[(pool, ps)] = dec.i64()
    crush = decode_crush(dec)
    m = OSDMap(crush)
    m.epoch = epoch
    m.max_osd = max_osd
    m.osd_state = osd_state
    m.osd_weight = osd_weight
    m.osd_primary_affinity = affinity
    m.pools = pools
    m.pg_upmap = pg_upmap
    m.pg_upmap_items = pg_upmap_items
    m.pg_temp = pg_temp
    m.primary_temp = primary_temp
    dec.finish()
    return m


def _typed(fn, buf: bytes, struct_name: str):
    """Decode with every failure surfaced as MalformedInput: bytes
    that survive the envelope but build an impossible map (a dup
    bucket id from a flipped byte, a ragged rule program) are still
    protocol errors, never raw ValueError/struct.error escapes."""
    try:
        return fn(Decoder(buf, struct_name=struct_name))
    except DecodeError:
        raise
    except (ValueError, TypeError, KeyError, IndexError,
            OverflowError) as e:
        raise DecodeError(f"{struct_name}: bad payload: {e!r}")


def osdmap_to_bytes(m: OSDMap) -> bytes:
    enc = Encoder()
    encode_osdmap(m, enc)
    return enc.bytes()


def osdmap_from_bytes(buf: bytes) -> OSDMap:
    return _typed(decode_osdmap, buf, "osdmap.full")


def crush_to_bytes(m: CrushMap) -> bytes:
    enc = Encoder()
    encode_crush(m, enc)
    return enc.bytes()


def crush_from_bytes(buf: bytes) -> CrushMap:
    return _typed(decode_crush, buf, "osdmap.crush")


def payload_map(payload: dict) -> OSDMap:
    """Decode a monitor map payload in either wire form (map_bin,
    binary) or store/debug form (map, JSON dict)."""
    if "map_bin" in payload:
        return osdmap_from_bytes(payload["map_bin"])
    return OSDMap.from_dict(payload["map"])
