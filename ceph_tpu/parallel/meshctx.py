"""Process-default data-plane mesh holder + batch padding arithmetic.

Deliberately dependency-free (no jax, no crush): the EC engine reads
the default mesh on EVERY ``encode_batched`` call, and plugin-only
processes (a monitor, a CPU-engine OSD) must not pay the CRUSH
mapper's import side effects (the x64 config flip) — or any import at
all — for a data plane they never shard.  ``parallel.placement``
re-exports everything here under its public names.
"""

from __future__ import annotations

_mesh = None


def set_mesh(mesh) -> None:
    global _mesh
    _mesh = mesh


def get_mesh():
    return _mesh


def pad_batch(n: int, n_dev: int) -> int:
    """The padded batch size for ``n`` items over ``n_dev`` devices:
    next power of two (bounds the compile-signature set to log2 N
    entries — the recompile-budget contract), raised to a multiple of
    the mesh size so the shard axis divides evenly (a no-op on pow2
    meshes).  Pad lanes are masked or zero, never tallied."""
    n = max(1, int(n))
    p = 1 << (n - 1).bit_length()
    if p % n_dev:
        p = ((p + n_dev - 1) // n_dev) * n_dev
    return p
