"""Multi-chip placement + EC step — the framework's distribution layer.

The reference scales by sharding PGs across OSD processes and fanning
EC chunks across shard OSDs over its AsyncMessenger TCP fabric
(src/osd/OSDMapMapping.h:18 thread-pool PG batching;
src/osd/ECBackend.cc:934 chunk fan-out; src/msg/async/* transport).
The TPU-native re-expression (SURVEY §2.6): the PG axis is data-parallel
over the device mesh, the EC stripe batch axis is data-parallel too,
and all cross-chip movement is XLA collectives over ICI — an
all-reduce for cluster-wide utilization tallies, an all-gather when the
full placement table must be host-visible.  No NCCL/MPI translation; the
mesh + shardings ARE the communication backend.

``PlacementPlane`` is the production entry (the DrJAX-style map-reduce
decomposition, arXiv:2403.07128, over the t5x mesh idiom): one pjit
launch maps millions of PGs across every chip, with

- the map arrays and weight vector REPLICATED (they are the cluster
  map — every chip holds it, exactly as every OSD/client holds the
  OSDMap),
- the PG axis sharded ``NamedSharding(mesh, P("pg"))``,
- utilization tallies all-reduced back to every chip,
- pow2-padded batch shapes so the compile-signature set stays inside
  the jaxcheck recompile budget, and pad lanes masked out of the
  tally (pad-and-mask covers batches not divisible by the mesh),
- a single-device mesh as the degenerate case: the same code path,
  no fork on CPU CI.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crush.map import ChooseArgMap, CrushMap
from ..crush.map_arrays import encode_map
from ..crush.mapper_jax import book_map_batch, build_rule_fn
from .meshctx import pad_batch  # noqa: F401  (re-export; see meshctx)
from . import meshctx


def make_mesh(devices: Optional[Sequence] = None,
              axis_name: str = "pg") -> Mesh:
    """A 1-D mesh over the PG (data) axis — the framework's default
    topology, matching how the reference shards everything by PG."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (axis_name,))


# -- process-default data-plane mesh ----------------------------------------
#
# The EC engine and the OSD-side EncodeBatcher pick this up when no
# explicit mesh is threaded through (the OSD data path has no natural
# place to carry a Mesh handle): install once at daemon/bench startup,
# every batched encode shards its stripe axis from then on.  None (the
# default) means unsharded — CPU CI and single-chip hosts never fork.
# The holder lives in dependency-free ``meshctx`` so the EC engine can
# read it without importing this module's CRUSH dependencies.

def set_data_plane_mesh(mesh: Optional[Mesh]) -> None:
    """Install (or clear, with None) the process-default mesh the EC
    batched-encode paths shard over."""
    meshctx.set_mesh(mesh)


def data_plane_mesh() -> Optional[Mesh]:
    return meshctx.get_mesh()


@contextlib.contextmanager
def data_plane(mesh: Optional[Mesh]):
    """Scoped ``set_data_plane_mesh`` for tests and bench stages."""
    prev = meshctx.get_mesh()
    set_data_plane_mesh(mesh)
    try:
        yield mesh
    finally:
        set_data_plane_mesh(prev)


def utilization(results, lens, max_devices: int):
    """Per-OSD placement tallies — the CrushTester stats pass
    (src/crush/CrushTester.cc:588-648) as one scatter-add."""
    R = results.shape[-1]
    pos = jnp.arange(R, dtype=jnp.int32)
    valid = (pos[None, :] < lens[:, None]) & (results >= 0) \
        & (results < max_devices)
    flat = jnp.where(valid, results, max_devices)
    counts = jnp.zeros(max_devices + 1, jnp.int32).at[flat].add(1)
    return counts[:max_devices]


def sharded_rule_fn(cmap: CrushMap, ruleno: int, result_max: int,
                    mesh: Mesh, axis_name: str = "pg",
                    choose_args: Optional[ChooseArgMap] = None,
                    gather_stats: bool = True, masked: bool = False,
                    encoded=None):
    """Compile the batched mapper sharded over ``mesh`` — the engine
    behind ``PlacementPlane``.

    Returns ``fn(arrays, weight, xs)`` (or ``fn(arrays, weight, xs,
    valid)`` when ``masked``) where ``xs`` is sharded on the PG axis,
    the map arrays and weight vector are replicated, results stay
    PG-sharded, and the utilization tally is all-reduced to every
    chip.  ``masked`` adds a per-lane validity mask (sharded like
    ``xs``) that zeroes pad lanes out of the tally — the pad-and-mask
    half of the pow2 padding story.
    """
    fn, static, arrays = build_rule_fn(cmap, ruleno, result_max,
                                       choose_args, encoded=encoded)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis_name))

    if masked:
        def step(A, weight, xs, valid):
            res, lens = fn(A, weight, xs)
            if gather_stats:
                counts = utilization(
                    res, jnp.where(valid, lens, 0),
                    static.max_devices)
                return res, lens, counts
            return res, lens

        in_sh = (repl, repl, shard, shard)
    else:
        def step(A, weight, xs):
            res, lens = fn(A, weight, xs)
            if gather_stats:
                counts = utilization(res, lens, static.max_devices)
                return res, lens, counts
            return res, lens

        in_sh = (repl, repl, shard)

    out_sh = (shard, shard, repl) if gather_stats else (shard, shard)
    sharded = jax.jit(step, in_shardings=in_sh, out_shardings=out_sh)
    return sharded, static, arrays


class PlacementPlane:
    """The mesh-sharded CRUSH distribution layer: compile-per-rule
    cache + replicated map-array residency over an N-device mesh.

    >>> plane = PlacementPlane(cmap)            # mesh = all devices
    >>> res, lens = plane.map_batch(0, xs, 3, weight)
    >>> res, lens, counts = plane.map_batch(0, xs, 3, weight,
    ...                                     gather_stats=True)

    One ``map_batch`` is ONE pjit launch: the xs batch is pow2-padded
    (bounded compile signatures) and sharded across the mesh, every
    chip maps its shard against the replicated map, and — with
    ``gather_stats`` — the per-OSD utilization tally is all-reduced so
    every chip (and the host) holds cluster-wide counts.  Works
    unchanged on a 1-device mesh and on batches not divisible by the
    mesh size (pad lanes are masked out of the tally and sliced off
    the results).
    """

    def __init__(self, cmap: CrushMap,
                 choose_args: Optional[ChooseArgMap] = None,
                 mesh: Optional[Mesh] = None, axis_name: str = "pg",
                 encoded=None):
        self.cmap = cmap
        self.choose_args = choose_args
        self.mesh = mesh if mesh is not None else make_mesh(
            axis_name=axis_name)
        self.axis_name = axis_name if axis_name in \
            self.mesh.axis_names else self.mesh.axis_names[0]
        self.n_dev = int(np.asarray(self.mesh.devices).size)
        self._device_ids = [
            int(d.id) for d in np.asarray(self.mesh.devices).ravel()]  # mesh.devices is a host-side numpy array of Device handles
        self._repl = NamedSharding(self.mesh, P())
        self._shard = NamedSharding(self.mesh, P(self.axis_name))
        self._encoded = encoded if encoded is not None \
            else encode_map(cmap, choose_args)
        self._arrays = jax.device_put(
            jax.tree_util.tree_map(jnp.asarray, self._encoded[1]),
            self._repl)
        self._cache = {}            # (rule, R, gather) -> (fn, static)
        self._compiled_sigs: set = set()

    @property
    def static(self):
        return self._encoded[0]

    @property
    def arrays(self):
        return self._arrays

    def rule_fn(self, ruleno: int, result_max: int,
                gather_stats: bool = False):
        key = (ruleno, result_max, bool(gather_stats))
        if key not in self._cache:
            fn, static, _ = sharded_rule_fn(
                self.cmap, ruleno, result_max, self.mesh,
                axis_name=self.axis_name,
                choose_args=self.choose_args,
                gather_stats=gather_stats, masked=True,
                encoded=self._encoded)
            self._cache[key] = (fn, static)
        return self._cache[key][0]

    def map_batch(self, ruleno: int, xs, result_max: int, weight,
                  gather_stats: bool = False):
        """Map a batch across the mesh: xs uint32[N], weight 16.16
        uint32[max_devices].  Returns ``(results i32[N, R], lens
        i32[N])`` plus the all-reduced ``counts i32[max_devices]``
        when ``gather_stats``.

        When N is already padded (pow2, mesh-divisible) the outputs
        stay device-resident and sharded — the hot loop never syncs;
        otherwise pad lanes are sliced off host-side.
        """
        xs_np = np.asarray(xs, np.uint32)  # jax-ok: host-side batch normalization before the sharded upload
        n = int(xs_np.shape[0])
        npad = pad_batch(n, self.n_dev)
        fn = self.rule_fn(ruleno, result_max, gather_stats)
        if npad != n:
            pad = np.zeros(npad, np.uint32)
            pad[:n] = xs_np
            xs_np = pad
        valid_np = np.zeros(npad, np.bool_)
        valid_np[:n] = True
        w_dev = jax.device_put(
            jnp.asarray(np.asarray(weight, np.uint32)), self._repl)  # jax-ok: host-side weight normalization before the replicated upload
        xs_dev = jax.device_put(jnp.asarray(xs_np), self._shard)
        valid = jax.device_put(jnp.asarray(valid_np), self._shard)

        t0 = time.monotonic()
        out = fn(self._arrays, w_dev, xs_dev, valid)
        dt = time.monotonic() - t0
        sig = (ruleno, result_max, npad, self.n_dev,
               bool(gather_stats))
        first = sig not in self._compiled_sigs
        if first:
            self._compiled_sigs.add(sig)
        book_map_batch(
            sig, dt, n, result_max, first,
            h2d_bytes=npad * 5 + int(np.asarray(weight).size) * 4,  # jax-ok: sizing arithmetic on the host-side weight input
            d2h_bytes=npad * (result_max + 1) * 4,
            device_ids=self._device_ids)

        if gather_stats:
            res, lens, counts = out
        else:
            res, lens = out
        if npad != n:
            # pad-and-mask fallback: correctness path, not the hot
            # loop — slice host-side so no per-n slice programs pile
            # up in the jit cache
            res = np.asarray(res)[:n]  # jax-ok: deliberate egress on the padded (cold) path only
            lens = np.asarray(lens)[:n]  # jax-ok: deliberate egress on the padded (cold) path only
        if gather_stats:
            return res, lens, counts
        return res, lens


def mesh_device_report(mesh: Mesh):
    """Per-device breakdown for the multichip lane's telemetry: one
    row per mesh device (id, platform, backend memory stats where the
    PJRT client exposes them, and — once mesh launches have run —
    per-device kernel launches/time/transfer volume) — the
    observability ROADMAP item 1's near-linear-scaling claim is
    judged against this.  Safe here: the caller already owns an
    initialized mesh, so no backend-init risk."""
    from ..common import device_metrics

    by_id = {d["id"]: d for d in device_metrics.per_device()}
    work = device_metrics.mesh_device_table()
    out = []
    for d in np.asarray(mesh.devices).ravel():  # jax-ok: mesh.devices is a host-side numpy array of Device handles, not device data
        rec = by_id.get(int(d.id), {"id": int(d.id),
                                    "platform": str(d.platform)})
        w = work.get(int(d.id))
        if w:
            rec = dict(rec)
            rec["kernel_launches"] = int(w["launches"])
            rec["kernel_time_s"] = round(float(w["kernel_time_s"]), 6)  # jax-ok: host-side dict value, not a device scalar
            rec["h2d_bytes"] = int(w["h2d_bytes"])
            rec["d2h_bytes"] = int(w["d2h_bytes"])
        out.append(rec)
    return out
