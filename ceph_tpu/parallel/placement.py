"""Multi-chip placement + EC step — the framework's distribution layer.

The reference scales by sharding PGs across OSD processes and fanning
EC chunks across shard OSDs over its AsyncMessenger TCP fabric
(src/osd/OSDMapMapping.h:18 thread-pool PG batching;
src/osd/ECBackend.cc:934 chunk fan-out; src/msg/async/* transport).
The TPU-native re-expression (SURVEY §2.6): the PG axis is data-parallel
over the device mesh, the EC stripe byte axis is the sequence-parallel
axis, and all cross-chip movement is XLA collectives over ICI — an
all-reduce for cluster-wide utilization tallies, an all-gather when the
full placement table must be host-visible.  No NCCL/MPI translation; the
mesh + shardings ARE the communication backend.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crush.map import ChooseArgMap, CrushMap
from ..crush.mapper_jax import build_rule_fn


def make_mesh(devices: Optional[Sequence] = None,
              axis_name: str = "pg") -> Mesh:
    """A 1-D mesh over the PG (data) axis — the framework's default
    topology, matching how the reference shards everything by PG."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (axis_name,))


def utilization(results, lens, max_devices: int):
    """Per-OSD placement tallies — the CrushTester stats pass
    (src/crush/CrushTester.cc:588-648) as one scatter-add."""
    R = results.shape[-1]
    pos = jnp.arange(R, dtype=jnp.int32)
    valid = (pos[None, :] < lens[:, None]) & (results >= 0) \
        & (results < max_devices)
    flat = jnp.where(valid, results, max_devices)
    counts = jnp.zeros(max_devices + 1, jnp.int32).at[flat].add(1)
    return counts[:max_devices]


def sharded_rule_fn(cmap: CrushMap, ruleno: int, result_max: int,
                    mesh: Mesh, axis_name: str = "pg",
                    choose_args: Optional[ChooseArgMap] = None,
                    gather_stats: bool = True):
    """Compile the batched mapper sharded over ``mesh``.

    Returns ``fn(arrays, weight, xs)`` where ``xs`` is sharded on the PG
    axis, the map arrays and weight vector are replicated (they are the
    cluster map — every chip holds it, exactly as every OSD/client holds
    the OSDMap), results stay PG-sharded, and the utilization tally is
    all-reduced to every chip.
    """
    fn, static, arrays = build_rule_fn(cmap, ruleno, result_max,
                                       choose_args)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis_name))

    def step(A, weight, xs):
        res, lens = fn(A, weight, xs)
        if gather_stats:
            counts = utilization(res, lens, static.max_devices)
            return res, lens, counts
        return res, lens

    out_sh = (shard, shard, repl) if gather_stats else (shard, shard)
    sharded = jax.jit(
        step,
        in_shardings=(repl, repl, shard),
        out_shardings=out_sh)
    return sharded, static, arrays


def mesh_device_report(mesh: Mesh):
    """Per-device breakdown for the multichip lane's telemetry: one
    row per mesh device (id, platform, backend memory stats where the
    PJRT client exposes them) — the observability ROADMAP item 1's
    near-linear-scaling claim will be judged against.  Safe here: the
    caller already owns an initialized mesh, so no backend-init risk."""
    from ..common import device_metrics

    by_id = {d["id"]: d for d in device_metrics.per_device()}
    out = []
    for d in np.asarray(mesh.devices).ravel():  # jax-ok: mesh.devices is a host-side numpy array of Device handles, not device data
        rec = by_id.get(int(d.id), {"id": int(d.id),
                                    "platform": str(d.platform)})
        out.append(rec)
    return out
