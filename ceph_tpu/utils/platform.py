"""Platform selection helper.

Some deployment images preload jax and pin ``jax_platforms`` to a
hardware backend at interpreter start, which makes the standard
``JAX_PLATFORMS`` env var a no-op.  ``apply_platform_env()`` restores
user control: set ``CEPH_TPU_PLATFORM=cpu`` (or any backend name) to
override via jax.config before the first backend client is created.
"""

import os


def apply_platform_env() -> None:
    plat = os.environ.get("CEPH_TPU_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
