"""The config system — option schema, layered sources, observers.

The role of the reference's ``md_config_t`` / ``ConfigProxy``
(src/common/config.h) with options declared in YAML and compiled to
``Option`` structs (src/common/options/*.yaml.in via options/y2c.py):
here the schema is declared in Python (``Option`` dataclass +
``OPTIONS`` table) — same information, no codegen step.

Layering (lowest to highest precedence, config.h semantics):
  compiled default < config file < environment < runtime ``set()``.

Runtime changes notify registered observers (config_obs.h), which is
how long-lived services pick up reweights/debug levels without
restart.  ``show()`` is the ``ceph daemon ... config show`` payload.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

ENV_PREFIX = "CEPH_TPU_OPT_"


@dataclass
class Option:
    """One declared option (src/common/options.h:14)."""

    name: str
    type_: type
    default: Any
    desc: str = ""
    level: str = "advanced"  # basic | advanced | dev

    def coerce(self, value: Any) -> Any:
        if self.type_ is bool and isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return self.type_(value)


def _opts(*options: Option) -> Dict[str, Option]:
    return {o.name: o for o in options}


# the framework's option schema — the global.yaml.in/osd.yaml.in role
OPTIONS: Dict[str, Option] = _opts(
    Option("debug_crush", int, 0, "crush subsystem log level"),
    Option("debug_osd", int, 0, "osd-service subsystem log level"),
    Option("debug_mon", int, 0, "monitor subsystem log level"),
    Option("debug_ec", int, 0, "erasure-code subsystem log level"),
    Option("log_max_recent", int, 500, "crash ring-buffer entries"),
    Option("osd_pool_default_size", int, 3, "replica count default"),
    Option("osd_pool_default_pg_num", int, 32, "pg count default"),
    Option("osd_heartbeat_interval", float, 0.5,
           "seconds between osd->mon heartbeats"),
    Option("osd_heartbeat_grace", float, 2.0,
           "seconds without heartbeat before mark-down"),
    Option("mon_osd_down_out_interval", float, 5.0,
           "seconds down before an osd is marked out (weight 0), "
           "triggering remap + backfill"),
    Option("mon_osd_report_timeout", float, 0.0,
           "seconds without a DIRECT osd->mon beacon before the "
           "monitor marks an osd down on its own (the liveness-of-"
           "last-resort path; peer failure reports are the primary "
           "detector); 0 = auto (5x osd_heartbeat_grace)"),
    Option("mon_osd_min_down_reporters", int, 2,
           "peer failure reports from this many distinct CRUSH "
           "failure-domain subtrees before the monitor marks an osd "
           "down (OSDMonitor::check_failure role)"),
    Option("mon_osd_reporter_subtree_level", str, "host",
           "CRUSH bucket type at which failure reporters are "
           "deduplicated: reports from osds under the same subtree "
           "of this type count as ONE reporter"),
    Option("osd_op_complaint_time", float, 0.5,
           "seconds an op may stay in flight before it is a SLOW op: "
           "the OpTracker historic-slow threshold AND the count the "
           "osd's beacon reports for the monitor's SLOW_OPS health "
           "check (one knob, both consumers)"),
    Option("osd_heartbeat_ping_threshold_ms", float, 1000.0,
           "heartbeat RTT window average (1/5/15 min) above this "
           "raises OSD_SLOW_PING_TIME and makes the peer visible in "
           "dump_osd_network (mon_warn_on_slow_ping_time role); also "
           "the default dump_osd_network filter threshold"),
    Option("osd_heartbeat_min_peers", int, 4,
           "pad the PG-derived heartbeat peer set with other up osds "
           "until it reaches this size, so sparse PG overlap (small "
           "pools, pool-less clusters) still yields enough failure "
           "reporters for the monitor's quorum"),
    Option("osd_max_markdown_count", int, 5,
           "markdowns within osd_max_markdown_period before the osd "
           "is dampened: re-boots deferred + auto-out, surfaced as "
           "the OSD_FLAPPING health check (osd_markdown_log role)"),
    Option("osd_max_markdown_period", float, 600.0,
           "sliding window (seconds) for osd_max_markdown_count; "
           "dampening clears once the window empties"),
    Option("osd_max_backfills", int, 1,
           "concurrent recovery streams per osd"),
    Option("osd_calc_pg_upmaps_aggressively", bool, True,
           "balancer explores with shuffling and local fallbacks"),
    Option("osd_calc_pg_upmaps_local_fallback_retries", int, 100,
           "balancer local retry budget"),
    Option("osd_erasure_code_plugins", str,
           "jerasure isa lrc shec clay", "plugins loaded at start"),
    Option("mon_max_map_epochs", int, 500,
           "full OSDMap epochs retained by the map store"),
    Option("osd_scrub_interval", float, 300.0,
           "seconds between automatic deep scrubs of each PG "
           "(osd_deep_scrub_interval role); 0 disables"),
    Option("osd_scrub_auto_repair", bool, True,
           "drop shards whose stored crc32c mismatches so recovery "
           "re-decodes them from survivors"),
    Option("mon_lease", float, 0.6,
           "quorum leader lease interval; peons call an election "
           "after 3 missed leases"),
    Option("mon_election_timeout", float, 0.8,
           "base retry window for monitor elections (rank-staggered)"),
    Option("bench_tpu_deadline", float, 300.0,
           "seconds before the bench abandons a hung backend"),
    Option("lockdep", bool, False,
           "runtime lock-order checking (analysis/lockdep.py); the "
           "CEPH_TPU_LOCKDEP env var is the usual switch — this "
           "option mirrors it for config-file-driven runs"),
    Option("asyncheck_loop_budget_ms", float, 50.0,
           "wallclock budget (ms) for one @nonblocking dispatch "
           "callback before the asyncheck enforcer records an "
           "overrun with both-end stacks (analysis/asyncheck.py; "
           "active only under CEPH_TPU_ASYNCHECK=1)"),
    Option("watchdog_threshold", float, 30.0,
           "seconds a lock may stay held or a handler may run before "
           "the stall watchdog dumps all-thread stacks "
           "(analysis/watchdog.py; also the dump_blocked default)"),
    Option("trace_sample_rate", float, 1.0,
           "probability a new trace ROOT is sampled (children inherit "
           "the root's decision, across daemons); unsampled spans "
           "propagate context but are never recorded"),
    Option("trace_ring_size", int, 512,
           "finished spans retained per tracer (the dump_tracing ring "
           "buffer, newest-wins)"),
    Option("admin_socket", bool, True,
           "daemons bind their unix admin socket on start (perf dump, "
           "dump_tracing, dump_ops_in_flight, dump_blocked ... — the "
           "surface the telemetry tool polls)"),
    Option("wal_group_commit_max_delay_us", int, 0,
           "microseconds the WAL group-commit leader waits for more "
           "transactions to join before the shared fsync; 0 = no "
           "artificial delay (the group is whatever queued while the "
           "previous fsync ran — the kv_sync_thread dynamics)"),
    Option("client_retry_deadline", float, 10.0,
           "total seconds a client op may spend SLEEPING between "
           "retries (the jittered-backoff budget, common/backoff.py); "
           "once exhausted the op re-raises its last error instead of "
           "pacing another attempt"),
    Option("client_aio_window", int, 16,
           "default bounded in-flight window for Client.aio_put / "
           "aio_write (the objecter max-in-flight role): how many "
           "async ops may be outstanding before aio_* blocks"),
    Option("ec_encode_batch_max_delay_us", int, 0,
           "microseconds the OSD's EC encode coalescer waits for more "
           "same-pool writes to join a batched encode dispatch; 0 = "
           "coalesce only what queued during the previous dispatch"),
    Option("metrics_history_interval", float, 1.0,
           "seconds between perf-counter samples into each daemon's "
           "metrics-history ring (common/metrics_history.py, the "
           "dump_metrics_history surface); 0 disables the sampler"),
    Option("metrics_history_retention", int, 240,
           "samples retained per daemon's metrics-history ring "
           "(newest-wins)"),
    Option("osd_pg_stat_report_interval", float, 2.0,
           "seconds between an OSD's periodic pg_stats beacons to the "
           "monitors (cached PG state + per-pool io/recovery "
           "counters; the mgr stats-report cadence role)"),
    Option("mon_pg_stats_stale_grace", float, 15.0,
           "seconds without a primary pg_stats report before a PG's "
           "stats are STALE (the STALE_PG_STATS health check); "
           "entries older than 4x this are aged out entirely"),
    Option("mon_slow_recovery_grace", float, 60.0,
           "seconds a recovery progress event may stay open before "
           "the SLOW_RECOVERY health check fires"),
    Option("mon_pool_stats_retention", int, 240,
           "per-pool stat samples retained by the monitor's PGMap "
           "ring (the `pool-stats` rate series)"),
    Option("debug_mgr", int, 0, "manager subsystem log level"),
    Option("mgr_tick_interval", float, 0.5,
           "mgr module scheduler pass interval; each module re-arms "
           "with a jittered draw around its own interval"),
    Option("mgr_modules", str, "balancer",
           "comma-separated mgr modules enabled at startup (the "
           "mgr_initial_modules role)"),
    Option("balancer_interval", float, 2.0,
           "seconds between balancer rounds when active (the "
           "balancer sleep_interval role)"),
    Option("balancer_max_deviation", int, 5,
           "PG-count deviation from the weight-proportional target "
           "below which an OSD is considered balanced "
           "(upmap_max_deviation)"),
    Option("balancer_max_iterations", int, 10,
           "calc_pg_upmaps optimizer iterations per round "
           "(upmap_max_optimizations)"),
    Option("osd_max_recovery_ops", int, 3,
           "recovery reservation slots per osd (local acquisitions "
           "and remote grants share one pool — the AsyncReserver "
           "osd_recovery_max_active role); a primary that cannot "
           "reserve every push target backs off and retries the pass"),
    Option("osd_recovery_sleep", float, 0.0,
           "seconds the recovery pipeline pauses between units "
           "(the osd_recovery_sleep pacing knob); 0 = no pacing"),
    Option("osd_recovery_pipeline_depth", int, 2,
           "bounded recovery pipeline depth: helper reads for up to "
           "this many units stream while earlier units decode; "
           "<= 1 degrades to serial gather-then-decode per unit"),
    Option("osd_recovery_batch_max_objects", int, 8,
           "objects batched into one recovery pipeline unit (one "
           "concatenated recover_stripes decode)"),
    Option("osd_recovery_helper_deadline", float, 2.0,
           "jittered-backoff budget (seconds) for re-planning an "
           "object's decode after helper-read failures before the "
           "object is deferred to the next recovery pass"),
    Option("fault_inject_spec", str, "",
           "armed failpoints (analysis/faults.py spec syntax, e.g. "
           "'msgr.corrupt_frame=p:0.02;osd.slow_op=p:0.1,delay:0.05')"
           "; empty disarms everything — the ms-inject-socket-"
           "failures / filestore_debug_inject_read_err surface",
           level="dev"),
    Option("profiler_hz", float, 100.0,
           "wallclock sampler rate when 'profile start' names no "
           "rate; sampling is jittered around 1/hz (the profiler is "
           "OFF until started via the admin socket or a bench hook)"),
    Option("profiler_max_seconds", float, 30.0,
           "wallclock sampler auto-stop budget: a forgotten "
           "'profile start' stops sampling after this many seconds"),
    Option("profiler_max_stacks", int, 4096,
           "bounded profiler retention: distinct folded stacks kept "
           "per daemon; further stacks fold into an overflow bucket"),
    Option("profiler_seed", int, 0,
           "seed for the profiler's jittered sampling interval "
           "(reproducible sample schedules across runs)", level="dev"),
)


class Config:
    """Layered option store with observers."""

    def __init__(self, schema: Optional[Dict[str, Option]] = None):
        self.schema = dict(schema or OPTIONS)
        self._file: Dict[str, Any] = {}
        self._env: Dict[str, Any] = {}
        self._override: Dict[str, Any] = {}
        self._observers: Dict[str, List[Callable[[str, Any], None]]] = {}
        self._load_env()

    # -- sources ------------------------------------------------------
    def _load_env(self) -> None:
        for key, value in os.environ.items():
            if key.startswith(ENV_PREFIX):
                name = key[len(ENV_PREFIX):].lower()
                if name in self.schema:
                    self._env[name] = self.schema[name].coerce(value)

    def load_file(self, path: str) -> int:
        """Read a config file: JSON object or ini-ish `name = value`
        lines (the ceph.conf role).  Returns options applied."""
        with open(path) as f:
            text = f.read()
        applied = 0
        stripped = text.lstrip()
        entries: Dict[str, Any] = {}
        if stripped.startswith("{"):
            entries = json.loads(text)
        else:
            for line in text.splitlines():
                line = line.split("#", 1)[0].split(";", 1)[0].strip()
                if not line or line.startswith("["):
                    continue
                name, _, value = line.partition("=")
                entries[name.strip().replace(" ", "_")] = value.strip()
        for name, value in entries.items():
            if name in self.schema:
                self._file[name] = self.schema[name].coerce(value)
                applied += 1
        return applied

    # -- access -------------------------------------------------------
    def get(self, name: str) -> Any:
        opt = self.schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        for layer in (self._override, self._env, self._file):
            if name in layer:
                return layer[name]
        return opt.default

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def set(self, name: str, value: Any) -> None:
        """Runtime override (`ceph config set` / injectargs role);
        notifies observers."""
        opt = self.schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        self._override[name] = opt.coerce(value)
        for cb in self._observers.get(name, []):
            cb(name, self._override[name])

    def rm_override(self, name: str) -> None:
        if self._override.pop(name, None) is not None:
            for cb in self._observers.get(name, []):
                cb(name, self.get(name))

    def add_observer(self, name: str,
                     cb: Callable[[str, Any], None]) -> None:
        self._observers.setdefault(name, []).append(cb)

    def remove_observer(self, name: str,
                        cb: Callable[[str, Any], None]) -> None:
        try:
            self._observers.get(name, []).remove(cb)
        except ValueError:
            pass

    def source_of(self, name: str) -> str:
        if name in self._override:
            return "override"
        if name in self._env:
            return "env"
        if name in self._file:
            return "file"
        return "default"

    def show(self) -> Dict[str, Dict[str, Any]]:
        """`config show`: every option with value + winning source."""
        return {name: {"value": self.get(name),
                       "source": self.source_of(name),
                       "default": opt.default,
                       "desc": opt.desc}
                for name, opt in sorted(self.schema.items())}
