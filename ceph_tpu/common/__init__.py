"""Foundation/runtime layer — the reference's src/common surface.

- ``config``: option schema + layered sources + runtime observers
  (md_config_t / ConfigProxy, src/common/config.h, options YAML).
- ``log``: per-subsystem leveled logging with a crash-dump ring buffer
  (src/log/Log.cc, SubsystemMap.h).
- ``perf_counters``: u64/avg/histogram counters with a per-process
  collection (src/common/perf_counters.h:63-141).
- ``admin_socket``: unix-socket command/introspection plane
  (src/common/admin_socket.h:105) serving perf dump / config show ...
- ``throttle``: counting backpressure primitive
  (src/common/Throttle.cc).
- ``context``: CephContext analogue tying them together.
"""
