"""dmClock op scheduler — QoS between op classes.

The role of src/osd/scheduler (OpScheduler/mClockScheduler over the
vendored dmclock submodule): each op class (client, recovery, scrub,
...) gets a QoS triple (reservation, weight, limit) in ops/sec, and the
queue serves by dmClock tag order — reservation tags first (guaranteed
floor), then weight-proportional sharing below the limit ceiling.

Tag algebra (the dmClock paper's core, as the reference configures it
via osd_mclock_scheduler_* options):

  R_tag = max(now, prev_R + 1/reservation)
  L_tag = max(now, prev_L + 1/limit)
  P_tag = max(now, prev_P + 1/weight)     (normalized share)

``dequeue(now)``: any class whose R_tag <= now is served by earliest
R_tag (reservation phase); otherwise the earliest P_tag among classes
with L_tag <= now (weight phase); otherwise None until a tag matures.
"""

from __future__ import annotations

import collections
import math
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Tuple

CLIENT = "client"
RECOVERY = "recovery"
SCRUB = "scrub"


@dataclass
class ClientInfo:
    """QoS triple in ops/sec; 0 disables the term."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0  # 0 = unlimited


class MClockQueue:
    def __init__(self, qos: Optional[Dict[str, ClientInfo]] = None):
        self.qos: Dict[str, ClientInfo] = dict(qos or {})
        self._queues: Dict[str, Deque] = collections.defaultdict(
            collections.deque)
        self._r_tag: Dict[str, float] = {}
        self._l_tag: Dict[str, float] = {}
        self._p_tag: Dict[str, float] = {}

    def set_qos(self, cls: str, info: ClientInfo) -> None:
        self.qos[cls] = info

    def enqueue(self, cls: str, item, now: float) -> None:
        if cls not in self.qos:
            self.qos[cls] = ClientInfo()
        q = self._queues[cls]
        q.append(item)
        if len(q) == 1:
            # idle -> active: tags catch up to now but NEVER rewind
            # (dmClock's max(prev, now) rule — a burst that drains and
            # re-fills must not defeat its limit)
            info = self.qos[cls]
            prev_r = self._r_tag.get(cls, now)
            if prev_r == math.inf:
                prev_r = now  # reservation granted since last active
            self._r_tag[cls] = (max(now, prev_r)
                                if info.reservation else math.inf)
            self._l_tag[cls] = max(now, self._l_tag.get(cls, now))
            self._p_tag[cls] = max(now, self._p_tag.get(cls, now))

    def _advance(self, cls: str, now: float) -> None:
        info = self.qos[cls]
        self._r_tag[cls] = (
            max(now, self._r_tag[cls] + 1.0 / info.reservation)
            if info.reservation else math.inf)
        self._l_tag[cls] = (
            max(now, self._l_tag[cls] + 1.0 / info.limit)
            if info.limit else now)
        self._p_tag[cls] = max(
            now, self._p_tag[cls] + 1.0 / max(1e-9, info.weight))

    def dequeue(self, now: float) -> Optional[Tuple[str, object]]:
        """The next op to serve at ``now``, or None if every class is
        tag-throttled (call again later)."""
        ready = [c for c, q in self._queues.items() if q]
        if not ready:
            return None
        # reservation phase: guaranteed floors first
        res = [c for c in ready if self._r_tag.get(c, math.inf) <= now]
        if res:
            cls = min(res, key=lambda c: self._r_tag[c])
        else:
            # weight phase: proportional share below the limit ceiling
            eligible = [c for c in ready
                        if self._l_tag.get(c, 0.0) <= now]
            if not eligible:
                return None
            cls = min(eligible, key=lambda c: self._p_tag[c])
        item = self._queues[cls].popleft()
        self._advance(cls, now)
        return cls, item

    def next_ready_at(self) -> float:
        """Earliest time a throttled dequeue could succeed."""
        times = []
        for c, q in self._queues.items():
            if not q:
                continue
            r = self._r_tag.get(c, math.inf)
            l_ = self._l_tag.get(c, 0.0)
            times.append(min(r, l_))
        return min(times) if times else math.inf

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())


def default_osd_queue() -> MClockQueue:
    """The balanced profile (osd_mclock_profile=balanced spirit):
    clients and recovery share, scrub runs in the leftovers."""
    return MClockQueue({
        CLIENT: ClientInfo(reservation=40.0, weight=1.0, limit=0.0),
        RECOVERY: ClientInfo(reservation=20.0, weight=0.5, limit=100.0),
        SCRUB: ClientInfo(reservation=0.0, weight=0.2, limit=50.0),
    })


class Requeue(Exception):
    """Raised by a job to be put back at the tail of its class queue —
    the bounded-resource-wait escape (a shard op whose PG lock is held
    by a long peering pass).  The WORKER moves on to other ops instead
    of blocking, so two stuck writes can no longer occupy the whole
    pool and starve every other PG's ops (the reference's ShardedOpWQ
    requeues ops that cannot take their PG lock the same way); the
    SUBMITTER keeps blocking on its original submit()."""


class OpScheduler:
    """Threaded front for MClockQueue — the OpScheduler/shard-worker
    seam (src/osd/scheduler/OpScheduler.h + OSD::ShardedOpWQ role):
    handler threads submit (class, thunk) and block for the result;
    a small worker pool serves strictly in dmClock tag order, so QoS
    between client/recovery/scrub ops is enforced at the store door."""

    def __init__(self, queue: Optional[MClockQueue] = None,
                 n_workers: int = 2):
        import threading

        from ..analysis.lockdep import make_lock

        # NOT `queue or ...`: an empty MClockQueue is len()==0 falsy
        self.q = queue if queue is not None else default_osd_queue()
        self._cv = threading.Condition(make_lock("opq::cv"))
        self._running = True
        self.served: Dict[str, int] = collections.defaultdict(int)
        self._workers = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"mclock-w{i}")
            for i in range(n_workers)]
        for w in self._workers:
            w.start()

    def submit(self, cls: str, fn):
        """Run ``fn`` under class ``cls``; blocks until served."""
        import threading
        import time as _time

        done = threading.Event()
        box: list = [None, None]  # result, exception

        def job(final: bool = False):
            try:
                box[0] = fn()
            except Requeue:
                if not final:
                    return True  # scheduler re-enqueues
                box[1] = RuntimeError(
                    "op abandoned at scheduler shutdown (resource "
                    "still busy)")
            except BaseException as e:  # propagated to the submitter
                box[1] = e
            done.set()
            return None

        inline = False
        with self._cv:
            if not self._running:
                raise RuntimeError("op scheduler shut down")
            now = _time.monotonic()
            self.q.enqueue(cls, job, now)
            if len(self.q) == 1:
                # inline fast path: nothing queued ahead, so run on
                # the SUBMITTING thread — dequeue still advances the
                # dmClock tags (QoS accounting intact; a tag-throttled
                # class stays queued for a worker to pace), and the
                # uncontended case saves two thread handoffs per op —
                # a real cost with many daemons sharing few cores
                got = self.q.dequeue(now)
                if got is not None:
                    inline = True
                    self.served[cls] += 1
                else:
                    self._cv.notify()
            else:
                self._cv.notify()
        if inline and job():
            # bounded wait failed (Requeue): back through the queue
            with self._cv:
                if self._running:
                    self.q.enqueue(cls, job, _time.monotonic())
                    self._cv.notify()
                else:
                    job(final=True)
        done.wait()
        if box[1] is not None:
            raise box[1]
        return box[0]

    def _work(self) -> None:
        import time as _time

        while True:
            with self._cv:
                while self._running:
                    got = self.q.dequeue(_time.monotonic())
                    if got is not None:
                        break
                    nxt = self.q.next_ready_at()
                    delay = max(0.001, min(
                        0.2, nxt - _time.monotonic())) \
                        if nxt != math.inf else 0.2
                    self._cv.wait(timeout=delay)
                if not self._running:
                    return
                cls, job = got
                self.served[cls] += 1
            if job():
                # bounded wait failed: back of the class queue (the
                # job itself paces via its own wait timeout)
                final = False
                with self._cv:
                    if self._running:
                        self.q.enqueue(cls, job, _time.monotonic())
                        self._cv.notify()
                    else:
                        final = True
                if final:
                    # OUTSIDE the cv, mirroring drain(): the final run
                    # re-executes fn(), which can block on a PG-lock
                    # wait or an fsync-heavy store write — holding the
                    # cv through that stalls every worker and shutdown
                    job(final=True)

    def depths(self) -> Dict[str, int]:
        with self._cv:
            return {c: len(q) for c, q in self.q._queues.items() if q}

    def shutdown(self) -> None:
        """Stop workers, then drain every queued job inline — a job
        abandoned un-run would leave its submitter blocked in
        done.wait() forever."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
            leftovers = []
            while True:
                got = self.q.dequeue(math.inf)
                if got is None:
                    break
                leftovers.append(got[1])
        for job in leftovers:
            job(final=True)
