"""Jittered exponential backoff with a retry *deadline* budget.

Fixed-interval retry (``time.sleep(0.3)`` in a loop) has two failure
modes the client paths shipped with: every retrying caller wakes in
lockstep — a thundering herd against a mon that just failed over —
and N retries x 0.3 s can silently exceed the op timeout the caller
thought it set.  This module is the one retry-pacing policy for the
framework (the osd_backoff / objecter retry-jitter role in the
reference, src/osd/osd_types.h Backoff):

  * decorrelated jitter — ``sleep = min(cap, uniform(base,
    prev * 3))`` — the AWS "Exponential Backoff and Jitter" result:
    retries desynchronize instead of re-colliding each round;
  * a deadline budget — the Backoff is built with the caller's total
    time budget and ``sleep()`` refuses to start a wait that cannot
    finish inside it, returning False so the caller raises its last
    error *within* its advertised timeout instead of 1.8x past it.

Usage (the shape tools/lint_faults.py FAULT001 pushes every
retry/except loop toward)::

    bo = Backoff(deadline=timeout)
    for attempt in range(retries):
        try:
            return do_op()
        except TransientError:
            if not bo.sleep():       # budget exhausted
                raise
"""

from __future__ import annotations

import random
import time
from typing import Optional


class Backoff:
    """One retry series: decorrelated-jitter pacing under a budget."""

    def __init__(self, base: float = 0.05, cap: float = 1.0,
                 deadline: Optional[float] = None,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        self._prev = base
        self._expires = (None if deadline is None
                         else time.monotonic() + deadline)
        self._rng = rng or random

    def remaining(self) -> float:
        """Seconds left in the budget (inf when unbudgeted)."""
        if self._expires is None:
            return float("inf")
        return max(0.0, self._expires - time.monotonic())

    def expired(self) -> bool:
        return self.remaining() <= 0

    def next_interval(self) -> float:
        """Draw the next jittered interval (advances the series)."""
        nxt = min(self.cap, self._rng.uniform(self.base,
                                              self._prev * 3))
        self._prev = max(nxt, self.base)
        return nxt

    def sleep(self) -> bool:
        """Sleep the next interval, truncated to the budget.  Returns
        False — without sleeping — once the budget is exhausted: the
        caller's cue to stop retrying and surface its last error."""
        nxt = self.next_interval()
        rem = self.remaining()
        if rem <= 0:
            return False
        time.sleep(min(nxt, rem))
        return True
