"""Central perf-counter registry — the single source of counter names.

The reference declares every counter in one PerfCountersBuilder block
per daemon (src/osd/OSD.cc:3260 osd_counters, src/mon/Monitor.cc
mon_counters, ...), so tooling — `ceph daemonperf` column schemas,
the mgr prometheus module — can rely on names that exist.  This module
is that declaration surface for the framework: every counter any
module books (``PerfCounters.inc/dec/set/tinc/avg_add/hist_add``) or
declares (``add_u64_counter``/``add_histogram``/...) must appear here,
keyed by logger family.

Enforced statically by ``tools/lint_obs.py`` (rule OBS001, wired into
``tests/test_lint.py``): an update or declaration with a literal name
absent from this registry fails CI, so the telemetry/daemonperf column
definitions can never silently drift from the counters the daemons
actually book.  ``tests/test_lint.py`` additionally pins the
``telemetry.DEFAULT_COLUMNS`` keys against this registry.

Logger families are matched by prefix: the ``osd`` family covers
``osd.0``, ``osd.1``...; ``client`` covers ``client.admin``; ``msgr``
covers ``msgr.osd.0`` — the instance suffix carries no schema.
"""

from __future__ import annotations

from typing import Dict, FrozenSet

U64 = "u64"
GAUGE = "gauge"
TIME = "time"
AVG = "avg"
HIST = "hist"

# {logger family: {counter name: type}} — the declaration mirror.
REGISTRY: Dict[str, Dict[str, str]] = {
    "mon": {
        "epochs": U64,
        "beats": U64,
        "markdowns": U64,
        "failure_reports": U64,
        "markdowns_dampened": U64,
        "commit_lat": HIST,
        "commit_time": TIME,
        "pg_stat_reports": U64,
        "stale_pgs": GAUGE,
    },
    "osd": {
        "ops_w": U64,
        "ops_r": U64,
        "degraded_reads": U64,
        "recovered_objects": U64,
        "recovery_bytes": U64,
        "map_epochs": U64,
        "pg_stat_beacons": U64,
    },
    "client": {
        "ops_put": U64,
        "ops_get": U64,
        "ops_write": U64,
        "ops_delete": U64,
        "op_errors": U64,
        "ops_aio_put": U64,
        "ops_aio_write": U64,
        "op_lat": HIST,
        "op_time": TIME,
        "aio_depth": HIST,
    },
    "msgr": {
        "bytes_in": U64,
        "bytes_out": U64,
        "frames_in": U64,
        "frames_out": U64,
        "dispatch_lat": HIST,
        "dispatch_time": TIME,
        # the saturation plane (PR 17): cumulative wall time _send
        # spent pushing frames against socket backpressure, the
        # send-queue depth observed per send, and the dispatch-queue
        # wait + on-wire->dispatch latency split by lane — the
        # "load masquerading as death" meters the epoll refactor
        # (ROADMAP item 1) must prove its win against
        "send_stall_time": TIME,
        "send_stalls": U64,
        "send_queue_depth": HIST,
        "dispatch_wait_ctl": HIST,
        "dispatch_wait_data": HIST,
        "dispatch_lat_ctl": HIST,
        "dispatch_lat_data": HIST,
    },
    "ec.engine": {
        "encode_ops": U64,
        "decode_ops": U64,
        "encode_bytes": U64,
        "decode_bytes": U64,
        "jit_compiles": U64,
        "encode_time": TIME,
        "decode_time": TIME,
        "jit_compile_time": TIME,
        "encode_lat": HIST,
        "decode_lat": HIST,
        "ec_batch_size": HIST,
    },
    "os.wal": {
        "txns": U64,
        "group_commits": U64,
        "group_commit_time": TIME,
        "wal_group_size": HIST,
    },
    "crush.mapper": {
        "map_calls": U64,
        "xs_mapped": U64,
        "jit_compiles": U64,
        "map_time": TIME,
        "jit_compile_time": TIME,
        "map_lat": HIST,
    },
    "crush.scalar": {
        "pg_lookups": U64,
        "cache_hits": U64,
        "map_time": TIME,
        "map_lat": HIST,
    },
    # the fault-injection plane (analysis/faults.py): one firing
    # counter per failpoint, booked process-globally so a chaos soak
    # can assert every armed fault actually fired (the names mirror
    # analysis.faults.FAILPOINTS — keep the two tables in sync)
    "faults": {
        "msgr.drop_frame": U64,
        "msgr.delay_frame": U64,
        "msgr.dup_frame": U64,
        "msgr.corrupt_frame": U64,
        "msgr.close_mid_frame": U64,
        "msgr.stall_dispatch": U64,
        "os.read_eio": U64,
        "os.fsync_eio": U64,
        "os.torn_append": U64,
        "osd.kill_before_commit": U64,
        "osd.kill_after_commit": U64,
        "osd.slow_op": U64,
        "osd.shard_read_eio": U64,
        "mon.drop_pg_stats": U64,
        "mon.isolate_rank": U64,
        "net.partition": U64,
        "mgr.balancer.stale_map": U64,
        "store.bit_rot": U64,
    },
    # the peer-heartbeat plane (services/heartbeat.py, the
    # OSD::heartbeat role): ping/ack volume, failure reports sent to
    # the mon, the live peer-set gauge, and ping RTT (whose windowed
    # average is the daemonperf `hb lat` column)
    "osd.hb": {
        "pings": U64,
        "acks": U64,
        "failures_reported": U64,
        "peers": GAUGE,
        "ping_time": TIME,
        "ping_lat": HIST,
    },
    # the recovery engine (osd_service._run_recovery): pipeline shape,
    # helper-read fan-out and exclusion accounting, reservation
    # back-pressure, and the per-unit repair-strategy choice with the
    # helper bytes the bandwidth-aware strategies saved over a full
    # k-shard decode
    "osd.recovery": {
        "pipelined_batches": U64,
        "serial_batches": U64,
        "helper_reads": U64,
        "helper_bytes": U64,
        "helper_bytes_saved": U64,
        "helper_eio_excluded": U64,
        "replans": U64,
        "strategy_full": U64,
        "strategy_lrc": U64,
        "strategy_clay": U64,
        "reservation_waits": U64,
        "remote_denials": U64,
    },
    # the manager daemon + module plane (ceph_tpu/mgr): scheduler
    # accounting plus the balancer loop's round/proposal counters and
    # its live balance gauges (deviation stddev, distribution score)
    "mgr": {
        "ticks": U64,
        "module_runs": U64,
        "module_errors": U64,
        "balancer_rounds": U64,
        "balancer_upmaps_proposed": U64,
        "balancer_sweep_launches": U64,
        "balancer_paused": U64,
        "balancer_stddev": GAUGE,
        "balancer_score": GAUGE,
    },
    # the device plane (common/device_metrics.py): host<->device
    # transfer volume, kernel launch accounting, and live-buffer /
    # device-memory gauges sampled into the metrics-history ring
    "device": {
        "h2d_bytes": U64,
        "d2h_bytes": U64,
        "kernel_launches": U64,
        "kernel_time": TIME,
        "live_buffers": GAUGE,
        "live_buffer_bytes": GAUGE,
        "live_buffer_bytes_hw": GAUGE,
    },
    # the pooled buffer plane (common/bufpool.py): recv-segment
    # recycling rates, live-segment gauges, and the GC-observed leak
    # count the per-test gate in tests/conftest.py red-checks
    "obs.bufpool": {
        "acquires": U64,
        "releases": U64,
        "pool_hits": U64,
        "pool_misses": U64,
        "leaked_segments": U64,
        "live_segments": GAUGE,
        "live_bytes": GAUGE,
    },
    # the byte-copy ledger (common/copytrack.py): every host-side
    # bytes copy on the hot write path books here, per site plus the
    # cross-site totals the daemonperf cp/op column divides.  Site
    # names mirror copytrack.SITES (OBS002 pins the two in sync).
    "obs.copy": {
        "bytes_copied": U64,
        "copies": U64,
        "recv_bytes": U64,
        "recv_copies": U64,
        "send_bytes": U64,
        "send_copies": U64,
        "store_txn_bytes": U64,
        "store_txn_copies": U64,
        "ec_assembly_bytes": U64,
        "ec_assembly_copies": U64,
        "recovery_push_bytes": U64,
        "recovery_push_copies": U64,
    },
    # the critical-path attribution plane (common/attribution.py):
    # one histogram per named stage a folded trace tree can charge
    # time to, plus the explicit residual.  Names mirror
    # attribution.STAGES (OBS002 pins the two in sync).
    "obs.latency": {
        "client": HIST,
        "messenger": HIST,
        "dispatch": HIST,
        "osd_op": HIST,
        "encode": HIST,
        "wal": HIST,
        "fanout": HIST,
        "unattributed": HIST,
        "attributed_ops": U64,
    },
    # the data-race checker (analysis/racecheck.py): violation count
    # (normally 0 — the daemonperf `race` column and the --race-audit
    # gate read it) plus registry-size gauges
    "analysis.race": {
        "violations": U64,
        "guarded_classes": GAUGE,
        "guarded_fields": GAUGE,
        "shared_objects": GAUGE,
    },
    # the async-safety checker (analysis/asyncheck.py): callback-
    # budget overruns (normally 0 — the daemonperf `blk` column and
    # thrasher --loop-stall read it) plus contract/scope gauges
    "analysis.block": {
        "overruns": U64,
        "contracts": GAUGE,
        "live_scopes": GAUGE,
    },
}


def all_names() -> FrozenSet[str]:
    """Every declared counter name, across all families (what OBS001
    checks literal update/declare sites against)."""
    out = set()
    for fam in REGISTRY.values():
        out.update(fam)
    return frozenset(out)


def family_of(logger: str) -> str:
    """Registry family for a concrete logger instance name
    (``osd.3`` -> ``osd``, ``msgr.mon`` -> ``msgr``)."""
    candidates = [f for f in REGISTRY
                  if logger == f or logger.startswith(f + ".")]
    return max(candidates, key=len) if candidates else ""


def declared(logger: str, key: str) -> bool:
    fam = family_of(logger)
    return bool(fam) and key in REGISTRY[fam]
