"""Pooled buffer plane — recycled, refcounted recv segments.

ROADMAP item 2's zero-copy data path starts here: the messenger recvs
every frame into a pooled ``Segment`` and hands the payload onward as
``memoryview`` slices, so the frame codec, the blob table, the store
``queue_transaction`` staging and the EC encode input all share ONE
host materialisation instead of re-copying at every layer boundary.

Lifecycle contract:

- ``acquire(n, tag)`` returns a ``Segment`` holding at least ``n``
  usable bytes with refcount 1.  Buffers come from per-size-class free
  lists (power-of-two classes); a hit recycles a previous buffer with
  zero allocation.
- ``Segment.incref()`` extends the lifetime across an async handoff
  (e.g. a dispatch worker still reading blob views after the reader
  thread moved on); every holder calls ``release()`` exactly once.
  Releasing below zero raises — a double release is a use-after-free
  in waiting, never a silent no-op.
- Views into a segment are only valid while the segment is held.
  Anything that must outlive the frame (reply caches, resend queues,
  the object store's own image) copies deliberately — and books that
  copy in the ``obs.copy`` ledger.

Leak accounting lives in the perf family (``obs.bufpool``): acquires/
releases/hit-miss rates, live-segment gauges, and ``leaked_segments``
— segments garbage-collected while still referenced, counted by a GC
finalizer so a lost segment surfaces in ``perf dump`` (and fails the
per-test gate in ``tests/conftest.py``) instead of silently costing
the recycle rate.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import weakref

from ..analysis.lockdep import make_lock
from ..analysis.racecheck import guarded_by, shared
from .perf_counters import PerfCounters, collection

LOGGER = "obs.bufpool"

# size classes are powers of two in [1 KiB, 16 MiB]; larger requests
# are served unpooled (counted as misses, never retained)
_MIN_SHIFT = 10
_MAX_SHIFT = 24
# free buffers retained per class — enough for every reader thread of
# a MiniCluster plus in-flight dispatch, small enough that an idle
# process holds <½ MiB of small classes
_PER_CLASS = 8


class DoubleRelease(AssertionError):
    """A segment was released more times than it was referenced."""


class Segment:
    """One refcounted pooled buffer (``nbytes`` usable)."""

    __slots__ = ("_buf", "nbytes", "tag", "_refs", "_pool", "_shift",
                 "_fin", "__weakref__")

    def __init__(self, buf: bytearray, nbytes: int, tag: str,
                 pool: "BufferPool", shift: int):
        self._buf = buf
        self.nbytes = nbytes
        self.tag = tag
        self._refs = 1
        self._pool = pool
        self._shift = shift
        # GC safety net: a segment collected while refs>0 is a leak —
        # count it and return its buffer to the pool so the leak costs
        # accounting, not capacity.  args (not the segment) keep the
        # buffer alive for the callback; detached on clean release.
        self._fin = weakref.finalize(self, pool._on_leak, buf, shift,
                                     tag, id(self))

    # -- views --------------------------------------------------------
    def writable(self) -> memoryview:
        """The recv_into target: the first ``nbytes`` of the buffer."""
        return memoryview(self._buf)[:self.nbytes]

    def view(self, start: int = 0, end: Optional[int] = None
             ) -> memoryview:
        """A zero-copy slice of the payload (valid while held)."""
        return memoryview(self._buf)[start:self.nbytes if end is None
                                     else end]

    # -- lifetime -----------------------------------------------------
    def incref(self) -> "Segment":
        with self._pool._lock:
            if self._refs <= 0:
                raise DoubleRelease(
                    f"bufpool: incref on released segment "
                    f"(tag={self.tag!r})")
            self._refs += 1
        return self

    def release(self) -> None:
        self._pool._release(self)

    @property
    def refs(self) -> int:
        return self._refs


@guarded_by("bufpool::pool", "_live")
class BufferPool:
    """Per-size-class recycling pool (process-global via ``pool()``)."""

    def __init__(self, per_class: int = _PER_CLASS):
        self._lock = make_lock("bufpool::pool")
        self._free: Dict[int, List[bytearray]] = shared(
            {}, "bufpool::pool", "bufpool.free")
        self._per_class = per_class
        # live-segment registry for the per-test leak gate: id -> tag
        self._live: Dict[int, Tuple[str, int]] = {}
        self._pc: Optional[PerfCounters] = None

    # -- counters -----------------------------------------------------
    def _counters(self) -> PerfCounters:
        with self._lock:
            if self._pc is None:
                pc = collection().create(LOGGER)
                for key in ("acquires", "releases", "pool_hits",
                            "pool_misses", "leaked_segments"):
                    pc.add_u64_counter(key)
                for key in ("live_segments", "live_bytes"):
                    pc.add_u64(key)
                self._pc = pc
            return self._pc

    # -- acquire / release --------------------------------------------
    @staticmethod
    def _shift_for(n: int) -> int:
        shift = max(_MIN_SHIFT, (max(1, n) - 1).bit_length())
        return shift

    def acquire(self, n: int, tag: str = "") -> Segment:
        """A segment with ``n`` usable bytes, refcount 1."""
        pc = self._counters()
        shift = self._shift_for(n)
        buf = None
        if shift <= _MAX_SHIFT:
            with self._lock:
                free = self._free.get(shift)
                if free:
                    buf = free.pop()
        if buf is None:
            pc.inc("pool_misses")
            buf = bytearray(1 << shift) if shift <= _MAX_SHIFT \
                else bytearray(n)
        else:
            pc.inc("pool_hits")
        seg = Segment(buf, n, tag, self, shift)
        with self._lock:
            self._live[id(seg)] = (tag, n)
        pc.inc("acquires")
        pc.inc("live_segments")
        pc.inc("live_bytes", n)
        return seg

    def _release(self, seg: Segment) -> None:
        pc = self._counters()
        with self._lock:
            if seg._refs <= 0:
                raise DoubleRelease(
                    f"bufpool: double release (tag={seg.tag!r})")
            seg._refs -= 1
            if seg._refs > 0:
                return
            self._live.pop(id(seg), None)
            seg._fin.detach()
            self._recycle_locked(seg._buf, seg._shift)
        pc.inc("releases")
        pc.dec("live_segments")
        pc.dec("live_bytes", seg.nbytes)

    def _recycle_locked(self, buf: bytearray, shift: int) -> None:
        if shift > _MAX_SHIFT or len(buf) != (1 << shift):
            return  # oversized / odd buffer: never retained
        free = self._free.setdefault(shift, [])
        if len(free) < self._per_class:
            free.append(buf)

    def _on_leak(self, buf: bytearray, shift: int, tag: str,
                 seg_id: int) -> None:
        """GC finalizer for a segment collected while still held."""
        pc = self._counters()
        with self._lock:
            self._recycle_locked(buf, shift)
            _tag, nbytes = self._live.pop(seg_id, (tag, 0))
        pc.inc("leaked_segments")
        pc.dec("live_segments")
        pc.dec("live_bytes", nbytes)

    # -- introspection (the conftest leak gate) -----------------------
    def outstanding(self) -> List[Tuple[str, int]]:
        """(tag, nbytes) of every currently-held segment."""
        with self._lock:
            return list(self._live.values())

    def leaked(self) -> int:
        pc = self._counters()
        return int(pc.dump().get("leaked_segments", 0))

    def free_buffers(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._free.values())


_pool = BufferPool()


def pool() -> BufferPool:
    """The process-global pool (all daemons of a MiniCluster share the
    process, exactly like the perf-counter collection)."""
    return _pool


def acquire(n: int, tag: str = "") -> Segment:
    return _pool.acquire(n, tag)


def outstanding() -> List[Tuple[str, int]]:
    return _pool.outstanding()
