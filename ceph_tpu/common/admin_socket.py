"""Admin socket — the unix-socket command/introspection plane.

The role of src/common/admin_socket.{h,cc} (AdminSocket,
admin_socket.h:105): a daemon binds a unix socket; ``ceph daemon
<name> <cmd>`` sends a JSON request line and reads a JSON reply.
Commands are registered with hooks; every daemon gets the built-ins
(help, perf dump, config show/set, log dump).

Protocol: one JSON object per connection — ``{"prefix": "<command>",
...args}`` in, JSON payload out (newline-terminated).
"""

from __future__ import annotations

import io
import json
import os
import socket
import threading
from typing import Callable, Dict, Optional

Hook = Callable[[Dict], object]


class AdminSocket:
    def __init__(self, path: str):
        self.path = path
        self._hooks: Dict[str, Hook] = {}
        self._descs: Dict[str, str] = {}
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.errors = 0  # serve-loop faults (see _serve)
        self.last_error: Optional[str] = None
        self.register("help", lambda _a: dict(self._descs),
                      "list registered commands")

    def register(self, prefix: str, hook: Hook,
                 desc: str = "") -> None:
        self._hooks[prefix] = hook
        self._descs[prefix] = desc

    # -- server side --------------------------------------------------
    def start(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.path)
        self._sock.listen(8)
        self._sock.settimeout(0.2)
        self._running = True
        self._thread = threading.Thread(target=self._serve,
                                        daemon=True,
                                        name=f"admin:{self.path}")
        self._thread.start()

    def _serve(self) -> None:
        while self._running:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                with conn:
                    data = b""
                    while not data.endswith(b"\n"):
                        got = conn.recv(65536)
                        if not got:
                            break
                        data += got
                    reply = self._dispatch(data.decode() or "{}")
                    conn.sendall(reply.encode() + b"\n")
            except Exception as e:
                # one bad client connection must not kill the serve
                # loop — but never vanish silently either (the
                # swallowed-thread-death lint class): keep the last
                # error inspectable
                self.errors += 1
                self.last_error = repr(e)

    def _dispatch(self, line: str) -> str:
        try:
            req = json.loads(line)
            prefix = req.get("prefix", "")
            hook = self._hooks.get(prefix)
            if hook is None:
                return json.dumps(
                    {"error": f"unknown command {prefix!r}",
                     "have": sorted(self._hooks)})
            return json.dumps(hook(req))
        except Exception as e:
            return json.dumps({"error": str(e)})

    def shutdown(self) -> None:
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2)
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    # -- client side (the `ceph daemon` role) --------------------------
    @staticmethod
    def request(path: str, prefix: str, timeout: float = 5.0,
                **args) -> object:
        with socket.socket(socket.AF_UNIX,
                           socket.SOCK_STREAM) as sock:
            sock.settimeout(timeout)
            sock.connect(path)
            sock.sendall(json.dumps(
                {"prefix": prefix, **args}).encode() + b"\n")
            data = b""
            while not data.endswith(b"\n"):
                got = sock.recv(65536)
                if not got:
                    break
                data += got
        return json.loads(data.decode())


def wire_defaults(sock: AdminSocket, config=None, perf=None,
                  logcore=None) -> None:
    """Register the built-in command set every daemon exposes."""
    from ..analysis.watchdog import dump_blocked

    # the stall-watchdog surface (analysis/watchdog.py): locks held /
    # handlers running past ?threshold seconds + all-thread stacks
    sock.register(
        "dump_blocked",
        lambda a: dump_blocked(
            threshold=float(a.get("threshold", 0.0)),
            with_stacks=bool(a.get("stacks", True))),
        "locks held and handlers stalled beyond a threshold, with "
        "per-thread stacks")
    if perf is not None:
        def _perf_dump(a):
            # the daemon's own collection, merged over the
            # PROCESS-GLOBAL library counters (ec.engine,
            # crush.mapper, crush.scalar — kernels shared by every
            # in-process daemon, perf_counters.collection()); the
            # daemon's loggers win on a name collision
            from .perf_counters import collection

            merged = dict(collection().dump())
            merged.update(perf.dump())
            lg = a.get("logger")
            if lg:
                return {lg: merged.get(lg, {})}
            return merged

        sock.register("perf dump", _perf_dump,
                      "dump perf counters (daemon + shared library "
                      "kernels; ?logger= filters)")
    if config is not None:
        sock.register("config show", lambda _a: config.show(),
                      "dump config options with sources")

        def _set(a):
            config.set(a["key"], a["value"])
            return {"success": f"{a['key']} = {config.get(a['key'])}"}

        sock.register("config set", _set, "override an option")
        sock.register(
            "config get",
            lambda a: {a["key"]: config.get(a["key"])},
            "read one option")
    if logcore is not None:
        def _log_dump(_a):
            buf = io.StringIO()
            n = logcore.dump_recent(buf)
            return {"entries": n, "dump": buf.getvalue()}

        sock.register("log dump", _log_dump,
                      "replay the recent-entry ring buffer")
