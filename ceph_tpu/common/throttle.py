"""Throttles — counting backpressure primitives.

The role of src/common/Throttle.{h,cc}: a named budget; ``get``
blocks (or fails) while the budget is exhausted, ``put`` returns it.
Used by services to bound in-flight recovery work
(osd_max_backfills-style limits).
"""

from __future__ import annotations

import threading

from ..analysis.lockdep import make_lock


class Throttle:
    def __init__(self, name: str, max_: int):
        self.name = name
        self.max = max_
        self.current = 0
        self._cond = threading.Condition(
            make_lock(f"throttle::{name}"))

    def get(self, count: int = 1, timeout: float | None = None) -> bool:
        """Block until the budget admits ``count``; False on timeout."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self.current + count <= self.max or
                self.max <= 0, timeout)
            if not ok:
                return False
            self.current += count
            return True

    def get_or_fail(self, count: int = 1) -> bool:
        with self._cond:
            if self.max > 0 and self.current + count > self.max:
                return False
            self.current += count
            return True

    def put(self, count: int = 1) -> None:
        with self._cond:
            self.current = max(0, self.current - count)
            self._cond.notify_all()

    def reset_max(self, max_: int) -> None:
        with self._cond:
            self.max = max_
            self._cond.notify_all()

    def get_current(self) -> int:
        with self._cond:
            return self.current

    def wait_until_drained(self, timeout: float | None = None) -> bool:
        """Block until every held unit is returned (the in-flight
        window is empty) — the flush/quiesce primitive async callers
        need; False on timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: self.current == 0,
                                       timeout)

    def hold(self, count: int = 1, timeout: float | None = None):
        """``with throttle.hold():`` — get on entry, put on exit.
        Raises TimeoutError when the budget never admits ``count``."""
        import contextlib

        @contextlib.contextmanager
        def _held():
            if not self.get(count, timeout):
                raise TimeoutError(
                    f"throttle {self.name}: {count} unit(s) not "
                    f"granted within {timeout}s")
            try:
                yield self
            finally:
                self.put(count)

        return _held()
