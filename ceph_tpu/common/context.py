"""CephContext analogue — one object tying the runtime together.

The reference threads a ``CephContext*`` through every component
(config proxy, log, perf counters collection, admin socket); services
here take a ``Context`` the same way so tests can build isolated
runtimes.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ..analysis.lockdep import make_lock, make_rlock  # noqa: F401 —
# the lock-registry hook: services build named, lockdep-tracked locks
# through the context module (or ..analysis.lockdep directly); raw
# threading.Lock() construction is flagged by tools/lint_concurrency.py
from .admin_socket import AdminSocket, wire_defaults
from .config import Config
from .log import LogCore, SubsysLogger
from .perf_counters import PerfCountersCollection
from .tracing import Tracer


class Context:
    make_lock = staticmethod(make_lock)
    make_rlock = staticmethod(make_rlock)
    def __init__(self, name: str = "ceph-tpu",
                 config: Optional[Config] = None,
                 admin_dir: Optional[str] = None):
        self.name = name
        self.conf = config or Config()
        if self.conf["lockdep"]:
            from ..analysis import lockdep

            lockdep.enable(True)
        # bind the fault-injection plane to this runtime's config:
        # applies the current fault_inject_spec and follows runtime
        # set() live (one observer per shared Config — idempotent)
        from ..analysis import faults

        faults.install(self.conf)
        self.log = LogCore(max_recent=self.conf["log_max_recent"])
        self.perf = PerfCountersCollection()
        # the daemon's tracing plane (common/tracing.py): services and
        # their messengers share this tracer, so one op's spans nest
        self.tracer = Tracer(name,
                             ring_size=self.conf["trace_ring_size"],
                             sample_rate=self.conf["trace_sample_rate"])
        self._admin: Optional[AdminSocket] = None
        self._admin_dir = admin_dir
        # the wallclock sampling profiler (common/profiler.py) — OFF
        # until 'profile start' arrives on the admin socket
        self.profiler = None
        # the daemon's counter time-series ring (dump_metrics_history)
        self._metrics_history = None
        # (option, callback) pairs to detach on shutdown — contexts may
        # share a Config (MiniCluster revives), so observers must not
        # outlive their runtime
        self._observers: list = []
        self._observed: set = set()

    def logger(self, subsys: str) -> SubsysLogger:
        lg = SubsysLogger(subsys, self.log)
        # debug_<subsys> option drives the level, live (observer)
        opt = f"debug_{subsys}"
        if opt in self.conf.schema and opt not in self._observed:
            self.log.set_level(subsys, self.conf[opt])

            def _cb(_n, v, _subsys=subsys):
                self.log.set_level(_subsys, int(v))

            self.conf.add_observer(opt, _cb)
            self._observers.append((opt, _cb))
            self._observed.add(opt)
        return lg

    @property
    def admin_socket_path(self) -> str:
        d = self._admin_dir or os.path.join(
            tempfile.gettempdir(), "ceph_tpu_asok")
        return os.path.join(d, f"{self.name}.asok")

    def start_admin_socket(self) -> AdminSocket:
        if self._admin is None:
            self._admin = AdminSocket(self.admin_socket_path)
            wire_defaults(self._admin, config=self.conf,
                          perf=self.perf, logcore=self.log)
            # the fault-injection command plane (`fault set|list|
            # clear` — the `ceph daemon ... injectargs`-era surface)
            from ..analysis import faults

            faults.wire(self._admin)
            # the data-race checker surface (analysis/racecheck.py):
            # guarded-class registry + recorded violations with both
            # access stacks, beside lockdep's dump_blocked
            from ..analysis import racecheck

            self._admin.register(
                "dump_racecheck", lambda _a: racecheck.dump(),
                "data-race checker: guarded classes and recorded "
                "lockset/confinement violations (both stacks)")
            # the async-safety surface (analysis/asyncheck.py):
            # @nonblocking contracts, live dispatch scopes (a stall in
            # progress is named before it finishes), and recorded
            # budget overruns with entry+witness stacks
            from ..analysis import asyncheck

            asyncheck.configure(
                self.conf["asyncheck_loop_budget_ms"])
            self._admin.register(
                "dump_asyncheck", lambda _a: asyncheck.dump(),
                "async-safety checker: non-blocking contracts, live "
                "scopes, and callback-budget overruns (both stacks)")
            if asyncheck.enabled():
                asyncheck.start_global()
            self._admin.start()
            # a daemon with an admin plane gets the stall watchdog
            # behind it: dump_blocked serves on demand, the scanner
            # reports wedges unprompted
            from ..analysis.watchdog import start_global

            start_global(self.conf["watchdog_threshold"])
            # the continuous plane: sample this runtime's counters
            # into a bounded ring, served as dump_metrics_history
            if self.conf["metrics_history_interval"] > 0:
                from .metrics_history import MetricsHistory

                self._metrics_history = MetricsHistory(
                    self.name, perf=self.perf,
                    interval=self.conf["metrics_history_interval"],
                    retention=self.conf["metrics_history_retention"])
                self._metrics_history.wire(self._admin)
                self._metrics_history.start()
            # the wallclock sampler command plane: `profile
            # start|stop|dump` per daemon (the reference's
            # wallclock-profiler attach surface).  Construction is
            # cheap; sampling only runs between start and stop.
            from .profiler import WallclockProfiler

            self.profiler = WallclockProfiler(
                hz=self.conf["profiler_hz"],
                max_seconds=self.conf["profiler_max_seconds"],
                max_stacks=self.conf["profiler_max_stacks"],
                seed=self.conf["profiler_seed"],
                name=self.name)

            def _profile(a, _prof=self.profiler):
                sub = a.get("cmd", "dump")
                if sub == "start":
                    hz = a.get("hz")
                    started = _prof.profile_start(
                        hz=float(hz) if hz else None)
                    return {"started": started, "hz": _prof.hz}
                if sub == "stop":
                    return {"stopped": _prof.profile_stop()}
                if sub == "dump":
                    return _prof.profile_dump()
                return {"error": f"unknown profile cmd: {sub}"}

            self._admin.register(
                "profile", _profile,
                "wallclock sampler: cmd=start|stop|dump [hz=N]")
        return self._admin

    @property
    def metrics_history(self):
        return self._metrics_history

    def shutdown(self) -> None:
        for opt, cb in self._observers:
            self.conf.remove_observer(opt, cb)
        self._observers.clear()
        self._observed.clear()
        if self._metrics_history is not None:
            self._metrics_history.stop()
            self._metrics_history = None
        if self.profiler is not None:
            self.profiler.profile_stop()
            self.profiler = None
        if self._admin is not None:
            self._admin.shutdown()
            self._admin = None
