"""OpTracker — in-flight op introspection and slow-op history.

The role of src/common/TrackedOp.h (OpTracker/TrackedOp): every op a
daemon services registers here with a type and description; events
mark its progress; ``dump_ops_in_flight`` and the slow-op history are
served over the admin socket (`ceph daemon ... dump_ops_in_flight`,
`dump_historic_ops`) — the first tool reached for when a cluster is
slow.
"""

from __future__ import annotations

import collections
import time
from typing import Deque, Dict, List, Optional

from ..analysis.lockdep import make_lock
from ..analysis.racecheck import guarded_by


class TrackedOp:
    def __init__(self, tracker: "OpTracker", op_type: str, desc: str):
        self._tracker = tracker
        self.op_type = op_type
        self.desc = desc
        self.start = time.time()
        self.events: List[tuple] = [(self.start, "initiated")]
        self.done: Optional[float] = None

    def mark_event(self, event: str) -> None:
        self.events.append((time.time(), event))

    def finish(self) -> None:
        """Idempotent: a second finish (an explicit finish inside a
        ``with`` block, or a double completion path) must not append a
        second "done" event, re-insert the op into history/slow, or
        double-count ``_served``."""
        if self.done is not None:
            return
        self.done = time.time()
        self.events.append((self.done, "done"))
        self._tracker._finish(self)

    def __enter__(self) -> "TrackedOp":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    @property
    def duration(self) -> float:
        return (self.done or time.time()) - self.start

    def dump(self) -> Dict:
        return {"type": self.op_type, "description": self.desc,
                "initiated_at": self.start,
                "age": round(self.duration, 6),
                "events": [{"time": t, "event": e}
                           for t, e in self.events]}


@guarded_by("optracker", "_inflight", "_history", "_slow", "_served")
class OpTracker:
    def __init__(self, history_size: int = 20,
                 history_slow_threshold: float = 0.5,
                 slow_history_size: Optional[int] = None):
        self._inflight: Dict[int, TrackedOp] = {}
        self._history: Deque[TrackedOp] = collections.deque(
            maxlen=history_size)
        # slow ops keep their OWN bounded ring, sized independently
        # (osd_op_history_slow_op_size vs osd_op_history_size in the
        # reference): only ops over the threshold enter it, so a burst
        # of fast ops can churn ``_history`` end to end without
        # evicting the slow ops an operator is hunting
        self._slow: Deque[TrackedOp] = collections.deque(
            maxlen=slow_history_size if slow_history_size is not None
            else history_size)
        self.slow_threshold = history_slow_threshold
        self._lock = make_lock("optracker")
        self._served = 0

    def create(self, op_type: str, desc: str = "") -> TrackedOp:
        op = TrackedOp(self, op_type, desc)
        with self._lock:
            self._inflight[id(op)] = op
        return op

    def _finish(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(id(op), None)
            self._history.append(op)
            self._served += 1
            if op.duration >= self.slow_threshold:
                self._slow.append(op)

    def slow_summary(self) -> Dict:
        """In-flight ops older than the slow threshold — the payload
        an OSD's beacon carries so the monitor can fold a SLOW_OPS
        health check (src/osd/OSD.cc get_health_metrics role).  Counts
        LIVE ops only: once they drain the count hits 0 and the check
        clears, exactly the reference's semantics."""
        now = time.time()
        with self._lock:
            ages = [now - op.start for op in self._inflight.values()]
        slow = [a for a in ages if a >= self.slow_threshold]
        return {"count": len(slow),
                "oldest_age": round(max(slow), 3) if slow else 0.0,
                "threshold": self.slow_threshold}

    # -- admin-socket payloads ----------------------------------------
    def dump_ops_in_flight(self) -> Dict:
        with self._lock:
            ops = [op.dump() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> Dict:
        with self._lock:
            return {"num_ops": len(self._history),
                    "served_total": self._served,
                    "ops": [op.dump() for op in self._history]}

    def dump_historic_slow_ops(self) -> Dict:
        with self._lock:
            return {"threshold": self.slow_threshold,
                    "ops": [op.dump() for op in self._slow]}

    def wire(self, admin_socket) -> None:
        admin_socket.register("dump_ops_in_flight",
                              lambda _a: self.dump_ops_in_flight(),
                              "in-flight ops")
        admin_socket.register("dump_historic_ops",
                              lambda _a: self.dump_historic_ops(),
                              "recently completed ops")
        admin_socket.register("dump_historic_slow_ops",
                              lambda _a: self.dump_historic_slow_ops(),
                              "recently completed slow ops")
