"""Device-plane metrics — the accelerator half of the telemetry plane.

The reference's perf counters stop at the syscall boundary; this
framework's hot path crosses another one — host -> XLA device -> host —
and the failure modes on that axis (recompilation storms, HBM
highwater creep, transfer-bound kernels) are invisible to the
OS-level counters.  This module is the process-global accounting the
jitted kernels (``ec.engine``, ``crush.mapper_jax``) book into:

- ``device`` perf logger: h2d/d2h transfer bytes, kernel launch
  count/time, live-buffer count/bytes gauges with a highwater mark
  (the DaemonHealthMetrics role for the device plane).
- a per-shape-signature table: wall time + transfer volume keyed by
  ``<logger>|<signature>`` — the same shape key XLA's jit cache uses,
  so a new row appearing in steady state IS a recompile (the
  jaxcheck budget gate's observability twin).  Bounded; sampled into
  every daemon's metrics-history ring (common/metrics_history.py).

``sample_memory()`` deliberately never *initializes* a backend: it
reads ``jax.live_arrays()`` only when jax is already imported, so a
monitor daemon that never touches device code pays nothing and a
wedged TPU tunnel can never hang the sampler.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

from ..analysis.lockdep import make_lock
from .perf_counters import collection

_pc = collection().create("device")
for _k in ("h2d_bytes", "d2h_bytes", "kernel_launches"):
    _pc.add_u64_counter(_k)
_pc.add_time("kernel_time")
for _k in ("live_buffers", "live_buffer_bytes",
           "live_buffer_bytes_hw"):
    _pc.add_u64(_k)

# <logger>|<signature> -> aggregate launch stats; bounded so a shape
# leak degrades to a truncated table, never unbounded memory
_MAX_SHAPES = 256
_shapes: Dict[str, Dict[str, float]] = {}
_shapes_lock = make_lock("device::shapes")
_buffer_hw = 0

# device id -> aggregate mesh-launch stats: the per-chip half of the
# multichip story.  A pjit launch over an N-device mesh is SPMD — every
# chip runs the program for ~the wall time while holding 1/N of the
# sharded data — so each participating device books the full wall time
# and its 1/N share of the transfer volume.  mesh_device_report joins
# this onto the per-device id/platform/memory rows, which is how the
# multichip bench lane proves real work landed on every chip.
_mesh_devices: Dict[int, Dict[str, float]] = {}


def record_launch(logger: str, sig: object, seconds: float,
                  h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
    """Book one device-kernel launch: callers pass the bytes they
    moved host->device (inputs) and device->host (materialized
    outputs) alongside the wall time."""
    _pc.inc("kernel_launches")
    _pc.tinc("kernel_time", seconds)
    if h2d_bytes:
        _pc.inc("h2d_bytes", h2d_bytes)
    if d2h_bytes:
        _pc.inc("d2h_bytes", d2h_bytes)
    key = f"{logger}|{sig}"
    with _shapes_lock:
        rec = _shapes.get(key)
        if rec is None:
            if len(_shapes) >= _MAX_SHAPES:
                return
            rec = _shapes[key] = {"count": 0, "time_s": 0.0,
                                  "h2d_bytes": 0, "d2h_bytes": 0}
        rec["count"] += 1
        rec["time_s"] += seconds
        rec["h2d_bytes"] += h2d_bytes
        rec["d2h_bytes"] += d2h_bytes


def record_mesh_launch(logger: str, sig: object, seconds: float,
                       device_ids, h2d_bytes: int = 0,
                       d2h_bytes: int = 0) -> None:
    """Book one mesh (pjit) launch: the aggregate booking of
    ``record_launch`` plus a per-device row for every mesh participant,
    so ``mesh_device_report`` shows kernel time on every chip rather
    than one hot device and N-1 idle rows."""
    ids = [int(i) for i in device_ids]
    record_launch(logger, sig, seconds,
                  h2d_bytes=h2d_bytes, d2h_bytes=d2h_bytes)
    n = max(1, len(ids))
    with _shapes_lock:
        for did in ids:
            rec = _mesh_devices.get(did)
            if rec is None:
                rec = _mesh_devices[did] = {
                    "launches": 0, "kernel_time_s": 0.0,
                    "h2d_bytes": 0, "d2h_bytes": 0}
            rec["launches"] += 1
            rec["kernel_time_s"] += seconds
            rec["h2d_bytes"] += h2d_bytes // n
            rec["d2h_bytes"] += d2h_bytes // n


def mesh_device_table() -> Dict[int, Dict[str, float]]:
    """Per-device mesh-launch aggregates (copied)."""
    with _shapes_lock:
        return {k: dict(v) for k, v in _mesh_devices.items()}


def shape_table() -> Dict[str, Dict[str, float]]:
    """Per-shape-signature launch aggregates (copied)."""
    with _shapes_lock:
        return {k: dict(v) for k, v in _shapes.items()}


def sample_memory() -> None:
    """Refresh the live-buffer gauges + highwater.  A no-op unless jax
    is already imported in this process: sampling must never trigger
    backend initialization (the historical TPU-tunnel hang point)."""
    global _buffer_hw
    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        arrs = jax.live_arrays()
    except Exception:
        return  # backend half-initialized / API moved: skip the sample
    total = 0
    n = 0
    for a in arrs:
        n += 1
        total += int(getattr(a, "nbytes", 0) or 0)
    _pc.set("live_buffers", n)
    _pc.set("live_buffer_bytes", total)
    if total > _buffer_hw:
        _buffer_hw = total
    _pc.set("live_buffer_bytes_hw", _buffer_hw)


def per_device() -> List[Dict]:
    """Per-device breakdown for the multichip lane: id, platform, and
    the backend's memory stats when it exposes them.  INITIALIZES the
    backend — only call from code that already owns device work
    (bench multichip lane, dryrun), never from a sampler."""
    jax = sys.modules.get("jax")
    if jax is None:
        import jax  # noqa: F811 — explicit opt-in to backend init
    out: List[Dict] = []
    for d in jax.devices():
        rec: Dict = {"id": int(d.id), "platform": str(d.platform)}
        try:
            stats = d.memory_stats()
            if stats:
                rec["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
                rec["peak_bytes_in_use"] = int(
                    stats.get("peak_bytes_in_use", 0))
        except Exception:
            pass  # CPU/virtual devices often expose no stats
        out.append(rec)
    return out


def reset_for_tests() -> None:
    global _buffer_hw
    with _shapes_lock:
        _shapes.clear()
        _mesh_devices.clear()
    _buffer_hw = 0
