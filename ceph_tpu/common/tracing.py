"""Distributed op tracing — the Jaeger/OpenTelemetry span model.

The role of src/tracing/ (Quincy's jaegertracing integration,
src/common/tracer.cc): every daemon owns a ``Tracer``; code opens
``Span``s around units of work; the messenger injects the active
span's context into outbound frames (a ``trace`` field) and opens a
child span around handler execution on the receiving daemon — so one
``Client.put`` on an EC pool yields a single trace whose spans live in
several processes' ring buffers, reassembled by trace_id with
``ceph_tpu/tools/telemetry.py``.

Model:

- ``Span``: (trace_id, span_id, parent_id) + name/service/tags, wall
  start time, monotonic duration, timestamped events (``log()``),
  idempotent ``finish()``.  Spans are context managers and the
  concurrency lint (CONC004) enforces that shape — a span that escapes
  its ``with`` is exactly the leak the per-test span gate catches.
- ``Tracer``: per-daemon factory + per-process ring buffer of finished
  spans (bounded, newest-wins) + the sampling decision.  Sampling is
  decided at the trace ROOT (probability ``sample_rate``) and
  inherited by every child, local or remote, via the wire carrier —
  an unsampled span still propagates its context (so downstream
  daemons agree) but is never recorded.
- Thread-local parenting: a span opened while another span of the
  same tracer is active on this thread becomes its child
  automatically; cross-thread and cross-daemon parents pass
  explicitly (``child_of`` = a Span or a wire carrier dict).

``require_parent=True`` returns a shared no-op span when there is no
active parent and no carrier — the fire-and-forget paths (heartbeats,
map pushes) stay out of the ring unless an op is actually being
traced through them.
"""

from __future__ import annotations

import collections
import itertools
import random
import threading
import time
import uuid
import weakref
from typing import Dict, List, Optional

from ..analysis.lockdep import make_lock

# every live tracer, for the process-wide span-leak gate
# (tests/conftest.py) and debugging; weak so runtimes can die
_tracers: "weakref.WeakSet" = weakref.WeakSet()
_tracers_lock = make_lock("tracing::registry")


_id_prefix = uuid.uuid4().hex[:8]
_id_counter = itertools.count(1)


def _gen_id() -> str:
    # random per-process prefix + counter: collision-safe for span
    # correlation at a fraction of uuid4's cost (ids are minted
    # several times per traced op on the data path)
    return f"{_id_prefix}{next(_id_counter):08x}"


class Span:
    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 sampled: bool, tags: Optional[Dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.tags: Dict = dict(tags or {})
        self.events: List[tuple] = []
        self.start = time.time()
        self._t0 = time.monotonic()
        self.duration: Optional[float] = None
        self.done: Optional[float] = None

    # -- recording ----------------------------------------------------
    def log(self, event: str) -> None:
        self.events.append((time.time(), event))

    def set_tag(self, key: str, value) -> None:
        self.tags[key] = value

    def finish(self) -> None:
        """Idempotent: a span double-finished (explicit finish inside a
        ``with``) records once and keeps its first duration."""
        if self.done is not None:
            return
        self.done = time.time()
        self.duration = time.monotonic() - self._t0
        self.tracer._finish(self)

    # -- context manager (the only lint-clean way to use a span) ------
    def __enter__(self) -> "Span":
        self.tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, _tb) -> bool:
        if exc is not None:
            self.set_tag("error", repr(exc))
        self.tracer._pop(self)
        self.finish()
        return False

    def dump(self) -> Dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "service": self.tracer.service, "start": self.start,
                "duration": (self.duration
                             if self.duration is not None
                             else time.monotonic() - self._t0),
                "finished": self.done is not None,
                "tags": dict(self.tags),
                "events": [{"time": t, "event": e}
                           for t, e in self.events]}


class _NoopSpan:
    """Shared sentinel for un-parented require_parent spans: carries no
    context, records nothing, safe from any thread."""

    tracer = None
    trace_id = None
    span_id = None
    parent_id = None
    sampled = False
    name = "<noop>"

    def log(self, event: str) -> None:
        pass

    def set_tag(self, key: str, value) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    def __init__(self, service: str, ring_size: int = 512,
                 sample_rate: float = 1.0):
        self.service = service
        self.sample_rate = sample_rate
        self._ring: "collections.deque[Span]" = collections.deque(
            maxlen=ring_size)
        self._active: Dict[str, Span] = {}
        self._lock = make_lock("tracing::tracer")
        self._tls = threading.local()
        self.started = 0
        self.finished = 0
        self.sampled_out = 0  # finished but not recorded (sampling)
        with _tracers_lock:
            _tracers.add(self)

    # -- thread-local span stack --------------------------------------
    def current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def _push(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)

    def _pop(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and span in stack:
            stack.remove(span)

    # -- span factory -------------------------------------------------
    def start_span(self, name: str, child_of=None,
                   tags: Optional[Dict] = None,
                   require_parent: bool = False):
        """Open a span.  ``child_of``: a Span, a wire carrier dict
        ({"trace_id", "span_id", "sampled"}), or None — None parents to
        this thread's active span, else starts a new root trace (where
        the sampling decision is made).  ``require_parent=True``
        returns the shared no-op span instead of a new root."""
        parent = child_of if child_of is not None else self.current()
        if isinstance(parent, _NoopSpan):
            parent = None
        if parent is None:
            if require_parent:
                return NOOP_SPAN
            trace_id, parent_id = _gen_id(), None
            sampled = random.random() < self.sample_rate
        elif isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
            sampled = parent.sampled
        else:  # wire carrier
            trace_id = parent.get("trace_id")
            parent_id = parent.get("span_id")
            sampled = bool(parent.get("sampled", True))
            if not trace_id:
                if require_parent:
                    return NOOP_SPAN
                trace_id, parent_id = _gen_id(), None
                sampled = random.random() < self.sample_rate
        span = Span(self, name, trace_id, _gen_id(), parent_id,
                    sampled, tags)
        with self._lock:
            self._active[span.span_id] = span
            self.started += 1
        return span

    def scope(self, span):
        """Adopt an EXISTING span as this thread's active parent (for
        work fanned out to a pool: the submitting thread captures
        ``tracer.current()``, the worker enters ``tracer.scope(it)``).
        Does not finish the span; no-ops on None / the no-op span."""
        return _Scope(self, span)

    # -- wire context -------------------------------------------------
    @staticmethod
    def inject(span) -> Optional[Dict]:
        """Span -> wire carrier (the frame's ``trace`` field); None for
        the no-op span (callers then skip the field entirely)."""
        if span is None or span.trace_id is None:
            return None
        return {"trace_id": span.trace_id, "span_id": span.span_id,
                "sampled": span.sampled}

    # -- completion ---------------------------------------------------
    def _finish(self, span: Span) -> None:
        with self._lock:
            self._active.pop(span.span_id, None)
            self.finished += 1
            if span.sampled:
                self._ring.append(span)
            else:
                self.sampled_out += 1

    # -- introspection ------------------------------------------------
    def active(self) -> List[Span]:
        with self._lock:
            return list(self._active.values())

    def abandon_active(self) -> List[Span]:
        """Drop every unfinished span (the per-test leak gate's reset:
        one leaky test must not re-fail every later one)."""
        with self._lock:
            left = list(self._active.values())
            self._active.clear()
        return left

    def dump(self, trace_id: Optional[str] = None,
             limit: Optional[int] = None) -> Dict:
        """The ``dump_tracing`` admin-socket payload."""
        with self._lock:
            spans = [s for s in self._ring
                     if trace_id is None or s.trace_id == trace_id]
            active = [s for s in self._active.values()
                      if trace_id is None or s.trace_id == trace_id]
            counters = {"started": self.started,
                        "finished": self.finished,
                        "sampled_out": self.sampled_out}
        if limit:
            spans = spans[-int(limit):]
        return {"service": self.service,
                "sample_rate": self.sample_rate,
                "spans": [s.dump() for s in spans],
                "active": [s.dump() for s in active],
                **counters}

    def wire(self, admin_socket) -> None:
        admin_socket.register(
            "dump_tracing",
            lambda a: self.dump(a.get("trace_id"), a.get("limit")),
            "finished-span ring buffer + active spans "
            "(?trace_id= filters, ?limit= trims)")


class _Scope:
    def __init__(self, tracer: Tracer, span):
        self.tracer = tracer
        self.span = None if isinstance(span, _NoopSpan) else span

    def __enter__(self):
        if self.span is not None:
            self.tracer._push(self.span)
        return self.span

    def __exit__(self, *exc) -> bool:
        if self.span is not None:
            self.tracer._pop(self.span)
        return False


def active_spans() -> List[tuple]:
    """(service, span) for every unfinished span in the process — the
    per-test span-leak gate's probe."""
    with _tracers_lock:
        tracers = list(_tracers)
    return [(t.service, s) for t in tracers for s in t.active()]


def abandon_all_active() -> List[tuple]:
    with _tracers_lock:
        tracers = list(_tracers)
    return [(t.service, s) for t in tracers
            for s in t.abandon_active()]
