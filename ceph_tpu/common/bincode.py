"""Binary versioned encoding — the encoding.h / denc.h role.

The reference serializes every wire/disk structure through
ENCODE_START/ENCODE_FINISH envelopes (src/include/encoding.h:1531
region): a struct_v byte, a compat_v floor, and a length so old
decoders can skip fields they don't know.  This module is the same
contract as real bytes (little-endian, length-prefixed), replacing the
JSON envelopes where size or crash-consistency matters: the WAL record
format, store checkpoints, and large-map distribution.

Primitives mirror the reference's `encode(x, bl)` overload set; the
envelope mirrors ENCODE_START(v, compat, bl) / DECODE_START(v, bl).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

from .encoding import MalformedInput

_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")


class Encoder:
    def __init__(self):
        self._parts: List[bytes] = []
        self._envs: List[int] = []  # indexes of length placeholders

    # -- scalars ------------------------------------------------------
    def u8(self, v: int) -> "Encoder":
        self._parts.append(_U8.pack(v))
        return self

    def u16(self, v: int) -> "Encoder":
        self._parts.append(_U16.pack(v))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(_U32.pack(v))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(_U64.pack(v))
        return self

    def i64(self, v: int) -> "Encoder":
        self._parts.append(_I64.pack(v))
        return self

    # -- blobs / strings ---------------------------------------------
    def blob(self, b) -> "Encoder":
        """Accepts any buffer-protocol object and stages it AS IS —
        the bufferlist::append(raw) role: views stay views until the
        single gathered join in ``bytes()``, so a WAL record over a
        pooled recv segment costs one materialisation, not two.  The
        buffer must stay valid until ``bytes()`` is called."""
        self._parts.append(_U32.pack(len(b)))
        self._parts.append(b)
        return self

    def str_(self, s: str) -> "Encoder":
        return self.blob(s.encode("utf-8"))

    # -- containers ---------------------------------------------------
    def str_blob_map(self, d: Dict[str, bytes]) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):
            self.str_(k)
            self.blob(d[k])
        return self

    def str_list(self, xs: List[str]) -> "Encoder":
        self.u32(len(xs))
        for x in xs:
            self.str_(x)
        return self

    # -- versioned envelope (ENCODE_START/FINISH) ---------------------
    def start(self, struct_v: int, compat_v: int) -> "Encoder":
        self.u8(struct_v).u8(compat_v)
        self._envs.append(len(self._parts))
        self._parts.append(b"\0\0\0\0")  # length placeholder
        return self

    def finish(self) -> "Encoder":
        at = self._envs.pop()
        length = sum(len(p) for p in self._parts[at + 1:])
        self._parts[at] = _U32.pack(length)
        return self

    def bytes(self) -> bytes:
        assert not self._envs, "unbalanced envelope"
        return b"".join(self._parts)


class DecodeError(MalformedInput):
    """Binary decode failure — a MalformedInput subtype, so transports
    and mounts handle JSON-envelope and bincode corruption as one
    typed protocol-error class."""


class Decoder:
    def __init__(self, buf: bytes, pos: int = 0,
                 struct_name: str = "structure"):
        if isinstance(buf, memoryview):
            # decode is the cold path (WAL replay, map
            # install) and every primitive below slices + unpacks —
            # normalizing once beats a view-aware copy per field
            buf = bytes(buf)
        self._b = buf
        self._pos = pos
        self._ends: List[int] = []
        self.struct_name = struct_name

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._b):
            raise DecodeError(
                f"{self.struct_name}: truncated: need {n} at "
                f"{self._pos}/{len(self._b)}")
        v = self._b[self._pos:self._pos + n]
        self._pos += n
        return v

    def u8(self) -> int:
        return _U8.unpack(self._take(1))[0]

    def u16(self) -> int:
        return _U16.unpack(self._take(2))[0]

    def u32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self._take(8))[0]

    def i64(self) -> int:
        return _I64.unpack(self._take(8))[0]

    def blob(self) -> bytes:
        return bytes(self._take(self.u32()))

    def str_(self) -> str:
        try:
            return self.blob().decode("utf-8")
        except UnicodeDecodeError as e:
            # tampered bytes must surface as the typed protocol error,
            # not an uncaught UnicodeDecodeError
            raise DecodeError(f"{self.struct_name}: bad utf-8: {e}")

    def str_blob_map(self) -> Dict[str, bytes]:
        return {self.str_(): self.blob() for _ in range(self.u32())}

    def str_list(self) -> List[str]:
        return [self.str_() for _ in range(self.u32())]

    def start(self, max_supported_v: int,
              struct_name: str = None) -> int:
        """DECODE_START: returns struct_v; raises when the encoder's
        compat floor is newer than what this decoder supports."""
        if struct_name is not None:
            self.struct_name = struct_name
        struct_v = self.u8()
        compat_v = self.u8()
        length = self.u32()
        if compat_v > max_supported_v:
            raise DecodeError(
                f"{self.struct_name} (writer struct_v {struct_v}) "
                f"requires decoder >= v{compat_v}, "
                f"have v{max_supported_v}")
        if self._pos + length > len(self._b):
            raise DecodeError(
                f"{self.struct_name}: envelope claims {length} bytes, "
                f"only {len(self._b) - self._pos} remain")
        self._ends.append(self._pos + length)
        return struct_v

    def finish(self) -> None:
        """DECODE_FINISH: skip fields this decoder didn't know about."""
        end = self._ends.pop()
        if self._pos > end:
            raise DecodeError(
                f"{self.struct_name}: decoded past envelope end")
        self._pos = end

    def remaining_in_envelope(self) -> int:
        return self._ends[-1] - self._pos if self._ends else \
            len(self._b) - self._pos

    @property
    def pos(self) -> int:
        return self._pos


# -- transaction codec -------------------------------------------------
# Transaction ops are tuples of (tag, str/int/bytes/dict/list fields);
# the codec writes a tagged, self-describing field list so the op set
# can grow without version bumps (Transaction::Op analogue).

_T_STR, _T_INT, _T_BYTES, _T_MAP, _T_LIST = range(5)


def encode_txn(ops: List[Tuple], enc: Encoder) -> None:
    enc.start(1, 1)
    enc.u32(len(ops))
    for op in ops:
        enc.u16(len(op))
        for field in op:
            if isinstance(field, str):
                enc.u8(_T_STR)
                enc.str_(field)
            elif isinstance(field, bool):
                raise TypeError("bool field in transaction op")
            elif isinstance(field, int):
                enc.u8(_T_INT)
                enc.i64(field)
            elif isinstance(field, (bytes, bytearray, memoryview)):
                enc.u8(_T_BYTES)
                enc.blob(field)  # staged as a view; Encoder.bytes()
                # is the one materialisation
            elif isinstance(field, dict):
                enc.u8(_T_MAP)
                enc.str_blob_map(field)
            elif isinstance(field, (list, tuple)):
                enc.u8(_T_LIST)
                enc.str_list(list(field))
            else:
                raise TypeError(f"unencodable op field {type(field)}")
    enc.finish()


def decode_txn(dec: Decoder) -> List[Tuple]:
    dec.start(1, struct_name="os.txn")
    ops = []
    for _ in range(dec.u32()):
        fields = []
        for _ in range(dec.u16()):
            tag = dec.u8()
            if tag == _T_STR:
                fields.append(dec.str_())
            elif tag == _T_INT:
                fields.append(dec.i64())
            elif tag == _T_BYTES:
                fields.append(dec.blob())
            elif tag == _T_MAP:
                fields.append(dec.str_blob_map())
            elif tag == _T_LIST:
                fields.append(dec.str_list())
            else:
                raise DecodeError(f"unknown field tag {tag}")
        ops.append(tuple(fields))
    dec.finish()
    return ops
