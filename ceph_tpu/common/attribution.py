"""Critical-path latency attribution — trace trees folded into stages.

PR 2's tracing plane records *that* an op was slow (a span tree per
``Client.put``); this module answers *where* the time went.  A
completed trace tree is folded onto the root op's wall-clock timeline:
every instant of the root interval is charged to exactly ONE stage —
the stage of the deepest span covering that instant — so the per-stage
totals sum to the measured client-side latency by construction (no
double counting across the parallel shard fan-out, no vanished gaps).
Time covered only by spans this table cannot name lands in an explicit
``unattributed`` stage instead of silently inflating a neighbor.

Stage mapping (ordered, most-specific first — the write path
client → messenger → dispatch queue → EC encode → WAL commit →
shard fan-out → ack):

  ==============  ==================================================
  stage           charged from
  ==============  ==================================================
  client          ``client.*`` root self-time (placement compute,
                  arg marshalling, completion plumbing)
  fanout          ``call:shard_write`` self-time (waiting on the
                  replica/shard round trips)
  encode          ``ec.encode`` (the batched EC encode dispatch)
  wal             ``store.commit`` (queue_transaction through the
                  group-commit fsync ack)
  messenger       any other ``call:*`` / ``send:*`` self-time
                  (serialization + socket + peer queue + network)
  dispatch        the ``q_wait`` tag on ``handle:*`` spans — frame
                  receipt to handler start (the OSD dispatch queue),
                  carved out of the surrounding messenger time
  osd_op          ``handle:*`` self-time after the q_wait carve
                  (PG lock, version stamping, store/RMW glue)
  unattributed    instants covered by no name this table knows,
                  plus any clock-skew residual
  ==============  ==================================================

Aggregation (``StageAggregator``) keeps online per-stage log2
histograms — the same bucket scheme ``PerfCounters.add_histogram``
uses — so the cluster-wide ``telemetry latency`` verb can report
per-stage p50/p99 and critical-path share without retaining folds.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

# every stage a fold can charge (mirrored by the ``obs.latency``
# family in common/counters.py — lint rule OBS002 pins the two)
STAGES: Tuple[str, ...] = ("client", "messenger", "dispatch",
                           "osd_op", "encode", "wal", "fanout",
                           "unattributed")

UNATTRIBUTED = "unattributed"


def stage_of(name: Optional[str]) -> Optional[str]:
    """Stage for one span name; None when the table cannot place it
    (the fold then charges ``unattributed``)."""
    if not name:
        return None
    if name.startswith("client."):
        return "client"
    if name == "call:shard_write":
        return "fanout"
    if name == "ec.encode":
        return "encode"
    if name == "store.commit":
        return "wal"
    if name.startswith(("call:", "send:")):
        return "messenger"
    if name.startswith("handle:"):
        return "osd_op"
    return None


def _interval(span: Dict) -> Optional[Tuple[float, float]]:
    start = span.get("start")
    dur = span.get("duration")
    if not isinstance(start, (int, float)) or \
            not isinstance(dur, (int, float)) or dur < 0:
        return None
    return float(start), float(start) + float(dur)


def fold_tree(root: Dict) -> Optional[Dict]:
    """Fold one reassembled trace tree (a ``telemetry.trace_tree``
    node: span dict + ``children`` list) into a per-stage breakdown.

    Returns ``{"trace_id", "root", "total", "stages": {stage: s}}``
    with ``sum(stages.values()) == total`` (to float rounding), or
    None for a root with no usable timing."""
    ri = _interval(root)
    if ri is None or not root.get("finished", True):
        return None
    r0, r1 = ri
    total = r1 - r0
    stages: Dict[str, float] = {s: 0.0 for s in STAGES}
    if total <= 0:
        return {"trace_id": root.get("trace_id"),
                "root": root.get("name"), "total": 0.0,
                "stages": stages}

    # flatten to (depth, clip0, clip1, span); clipping to the root
    # interval bounds cross-daemon clock skew
    flat: List[Tuple[int, float, float, Dict]] = []

    def walk(node: Dict, depth: int) -> None:
        iv = _interval(node)
        if iv is not None:
            a, b = max(iv[0], r0), min(iv[1], r1)
            if b > a:
                flat.append((depth, a, b, node))
        for child in node.get("children", []):
            walk(child, depth + 1)

    walk(root, 0)

    # elementary segments between all span boundaries: each is charged
    # to the DEEPEST covering span (ties break toward the later
    # start — parallel siblings at equal depth share a stage anyway)
    bounds = sorted({t for _d, a, b, _s in flat for t in (a, b)})
    q_wait_total = 0.0
    for seg0, seg1 in zip(bounds, bounds[1:]):
        mid = (seg0 + seg1) / 2
        best = None
        for depth, a, b, span in flat:
            if a <= mid < b and (best is None or depth >= best[0]):
                best = (depth, span)
        st = stage_of(best[1].get("name")) if best else None
        stages[st if st in STAGES else UNATTRIBUTED] += seg1 - seg0

    # the dispatch-queue carve: handle spans tag the frame-receipt ->
    # handler-start wait (q_wait), which wall-clock-wise sits inside
    # the caller's messenger time.  Move it (bounded by what the
    # messenger stage actually holds — parallel fan-out q_waits can
    # overlap) so queueing is visible as its own stage.
    for _d, _a, _b, span in flat:
        name = span.get("name") or ""
        if name.startswith("handle:"):
            qw = (span.get("tags") or {}).get("q_wait")
            if isinstance(qw, (int, float)) and qw > 0:
                q_wait_total += float(qw)
    moved = min(q_wait_total, stages["messenger"])
    stages["messenger"] -= moved
    stages["dispatch"] += moved

    # float-rounding residual (the charge loop covers the root
    # interval exactly, so this is noise-scale) lands explicit
    residual = total - sum(stages.values())
    if residual > 0:
        stages[UNATTRIBUTED] += residual
    return {"trace_id": root.get("trace_id"),
            "root": root.get("name"), "total": total,
            "stages": stages}


def fold_spans(spans: Iterable[Dict],
               root_prefix: str = "client.") -> List[Dict]:
    """Group a flat span list (any number of daemons) by trace, parent
    into trees, and fold every finished root whose name matches
    ``root_prefix``.  Self-contained (no telemetry import) so the
    bench worker can fold in-process."""
    by_trace: Dict[str, List[Dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(s)
    out: List[Dict] = []
    for tid, mine in by_trace.items():
        index: Dict[str, Dict] = {}
        for s in mine:
            index.setdefault(s["span_id"], dict(s, children=[]))
        roots: List[Dict] = []
        for node in index.values():
            parent = node.get("parent_id")
            if parent and parent in index:
                index[parent]["children"].append(node)
            else:
                roots.append(node)
        for root in roots:
            name = root.get("name") or ""
            if not name.startswith(root_prefix):
                continue
            if not root.get("finished", True):
                continue
            fold = fold_tree(root)
            if fold is not None:
                out.append(fold)
    return out


class _LogHist:
    """Online log2 histogram over seconds — the
    ``PerfCounters.add_histogram`` bucket scheme (bucket 0 holds
    values <= min, bucket i holds (min*2^(i-1), min*2^i]) kept as a
    plain value object so aggregation needs no counter registry."""

    __slots__ = ("buckets", "lo", "count", "total")

    def __init__(self, buckets: int = 32, min_value: float = 1e-6):
        self.buckets = [0] * buckets
        self.lo = float(min_value)
        self.count = 0
        self.total = 0.0

    def add(self, value: float) -> None:
        import math

        if value <= self.lo:
            bucket = 0
        else:
            bucket = min(len(self.buckets) - 1,
                         1 + int(math.floor(math.log2(value /
                                                      self.lo))))
        self.buckets[bucket] += 1
        self.count += 1
        self.total += value

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` (0..1): linear
        interpolation inside the covering log2 bucket."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            if n == 0:
                continue
            if cum + n >= target:
                lo = 0.0 if i == 0 else self.lo * (2.0 ** (i - 1))
                hi = self.lo * (2.0 ** i) if i else self.lo
                frac = (target - cum) / n
                return lo + frac * (hi - lo)
            cum += n
        return self.lo * (2.0 ** (len(self.buckets) - 1))

    def dump(self) -> Dict:
        return {"buckets": list(self.buckets), "min": self.lo}


class StageAggregator:
    """Online cluster-wide aggregation of folds: per-stage log2
    histograms + totals, rendered as the ``latency`` verb's report."""

    def __init__(self):
        self.hists: Dict[str, _LogHist] = {s: _LogHist()
                                           for s in STAGES}
        self.total_hist = _LogHist()
        self.n_ops = 0

    def add(self, fold: Dict) -> None:
        self.n_ops += 1
        self.total_hist.add(fold["total"])
        for stage, secs in fold["stages"].items():
            if secs > 0 and stage in self.hists:
                self.hists[stage].add(secs)

    def report(self) -> Dict:
        """{"n_ops", "total": {...}, "stages": {stage: {count,
        total_s, share, p50_ms, p99_ms}}} — ``share`` is the stage's
        fraction of all attributed wall-clock (the critical-path
        share the tentpole asks for)."""
        grand = self.total_hist.total or 1e-12
        stages: Dict[str, Dict] = {}
        for stage in STAGES:
            h = self.hists[stage]
            stages[stage] = {
                "count": h.count,
                "total_s": round(h.total, 6),
                "share": round(h.total / grand, 4),
                "p50_ms": round(h.quantile(0.50) * 1e3, 3),
                "p99_ms": round(h.quantile(0.99) * 1e3, 3),
            }
        return {
            "n_ops": self.n_ops,
            "total": {
                "total_s": round(self.total_hist.total, 6),
                "p50_ms": round(self.total_hist.quantile(0.5) * 1e3,
                                3),
                "p99_ms": round(self.total_hist.quantile(0.99) * 1e3,
                                3),
            },
            "stages": stages,
        }


def render_report(report: Dict) -> str:
    """The ``ceph_cli latency`` table: one row per stage, ordered by
    share, with the op-level p50/p99 header."""
    tot = report.get("total", {})
    lines = [f"latency attribution over {report.get('n_ops', 0)} ops "
             f"(op p50 {tot.get('p50_ms', 0.0)} ms, "
             f"p99 {tot.get('p99_ms', 0.0)} ms)",
             f"{'stage':<14}{'share':>8}{'total_s':>10}"
             f"{'p50_ms':>9}{'p99_ms':>9}{'count':>7}"]
    rows = sorted((report.get("stages") or {}).items(),
                  key=lambda kv: kv[1].get("share", 0.0),
                  reverse=True)
    for stage, row in rows:
        lines.append(f"{stage:<14}{row.get('share', 0.0):>8.1%}"
                     f"{row.get('total_s', 0.0):>10.4f}"
                     f"{row.get('p50_ms', 0.0):>9.3f}"
                     f"{row.get('p99_ms', 0.0):>9.3f}"
                     f"{row.get('count', 0):>7d}")
    return "\n".join(lines)
