"""Subsystem logging with a crash ring buffer.

The role of src/log/Log.cc + SubsystemMap.h: every subsystem has a
level; ``dout(subsys, level)``-style gating is ``logger.dout(level)``
on a per-subsystem logger; the most recent N entries (at ANY level,
even suppressed ones) are kept in a ring buffer that ``dump_recent``
replays on crash — the reference's signature feature that makes
post-mortem debugging possible without verbose live logs.
"""

from __future__ import annotations

import collections
import sys
import time
from typing import Deque, Dict, Optional, Tuple

from ..analysis.lockdep import make_lock

_Entry = Tuple[float, str, int, str]  # (stamp, subsys, level, message)


class LogCore:
    """Process-wide sink: level gating + the recent-entry ring."""

    def __init__(self, max_recent: int = 500, stream=None):
        self.levels: Dict[str, int] = {}
        self.max_recent = max_recent
        self._recent: Deque[_Entry] = collections.deque(
            maxlen=max_recent)
        self._lock = make_lock("log::core")
        self.stream = stream if stream is not None else sys.stderr

    def set_level(self, subsys: str, level: int) -> None:
        self.levels[subsys] = level

    def get_level(self, subsys: str) -> int:
        return self.levels.get(subsys, 0)

    def submit(self, subsys: str, level: int, message: str) -> None:
        entry = (time.time(), subsys, level, message)
        with self._lock:
            self._recent.append(entry)
        if level <= self.get_level(subsys):
            self.stream.write(self.format(entry) + "\n")

    @staticmethod
    def format(entry: _Entry) -> str:
        stamp, subsys, level, message = entry
        return f"{stamp:.6f} {subsys} {level} : {message}"

    def dump_recent(self, stream=None) -> int:
        """Replay the ring (Log::dump_recent, the crash handler path).
        Returns entries written."""
        out = stream if stream is not None else self.stream
        with self._lock:
            entries = list(self._recent)
        out.write(f"--- begin dump of recent {len(entries)} log "
                  f"entries ---\n")
        for e in entries:
            out.write(self.format(e) + "\n")
        out.write("--- end dump of recent events ---\n")
        return len(entries)


_core: Optional[LogCore] = None


def core() -> LogCore:
    global _core
    if _core is None:
        _core = LogCore()
    return _core


class SubsysLogger:
    """``dout(level) << ...`` as ``log.dout(level, msg)``."""

    def __init__(self, subsys: str, core_: Optional[LogCore] = None):
        self.subsys = subsys
        self.core = core_ or core()

    def dout(self, level: int, message: str) -> None:
        self.core.submit(self.subsys, level, message)

    def derr(self, message: str) -> None:
        self.core.submit(self.subsys, -1, message)

    def enabled(self, level: int) -> bool:
        return level <= self.core.get_level(self.subsys)


def getLogger(subsys: str) -> SubsysLogger:
    return SubsysLogger(subsys)
