"""Per-op byte-copy ledger — counting every hot-path host copy.

ROADMAP item 2 (zero-copy Pallas-default EC) is accepted on a
"measured drop in per-op bytes copied"; this module is that baseline
meter.  Every site on the write path that materialises a new host
buffer — messenger recv/send, store queue_transaction staging, EC
encode input assembly, recovery push payloads — books the copied byte
count and a copy count here, into the ``obs.copy`` family declared in
``common/counters.py``.  The daemonperf ``cp/op`` column divides the
cross-site ``bytes_copied`` total by the daemon's op throughput, and
``tools/perf_history.py`` red-checks growth of the bench-reported
bytes-copied-per-op so a refactor cannot silently reintroduce a copy.

Sites book against a *collection* (a daemon Context's
``PerfCountersCollection``) so the counters ride the existing asok
``perf dump`` plumbing; library code without a context books against
the process-global collection, matching the ``os.wal`` precedent.
"""

from __future__ import annotations

import weakref
from typing import Optional, Tuple

from ..analysis.lockdep import make_lock
from .perf_counters import PerfCounters, PerfCountersCollection, \
    collection

# every booking site (mirrored by the ``obs.copy`` family in
# common/counters.py — lint rule OBS002 pins the two)
SITES: Tuple[str, ...] = ("recv", "send", "store_txn", "ec_assembly",
                          "recovery_push")

LOGGER = "obs.copy"

_lock = make_lock("copytrack::ledgers")
# one ledger PerfCounters per collection, created lazily on first
# booking; weak keys so a shut-down daemon's collection can collect
_ledgers: "weakref.WeakKeyDictionary[PerfCountersCollection, PerfCounters]" = \
    weakref.WeakKeyDictionary()


def ledger(coll: Optional[PerfCountersCollection] = None) -> PerfCounters:
    """The ``obs.copy`` counters for ``coll`` (process-global
    collection when None), created and registered on first use."""
    target = coll if coll is not None else collection()
    with _lock:
        pc = _ledgers.get(target)
        if pc is None:
            pc = target.create(LOGGER)
            for _k in ("bytes_copied", "copies"):
                pc.add_u64_counter(_k)
            for _site in SITES:
                for _suffix in ("bytes", "copies"):
                    pc.add_u64_counter(f"{_site}_{_suffix}")
            _ledgers[target] = pc
        return pc


def book_pc(pc: PerfCounters, site: str, nbytes: int,
            copies: int = 1) -> None:
    """Book against an already-resolved ledger — the hot-loop form
    (the messenger reader caches its ledger at construction): four
    integer adds, no lock, no lookup."""
    if nbytes <= 0 and copies <= 0:
        return
    pc.inc("bytes_copied", nbytes)
    pc.inc("copies", copies)
    pc.inc(f"{site}_bytes", nbytes)
    pc.inc(f"{site}_copies", copies)


def book(site: str, nbytes: int, copies: int = 1,
         coll: Optional[PerfCountersCollection] = None) -> None:
    """Record ``copies`` host copies totalling ``nbytes`` at ``site``
    (one of SITES), resolving the ledger for ``coll`` (process-global
    when None)."""
    book_pc(ledger(coll), site, nbytes, copies)
