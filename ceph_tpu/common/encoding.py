"""Versioned encode/decode envelopes — the denc/encoding.h seam.

The reference wraps every wire/disk structure in
``ENCODE_START(v, compat_v)`` / ``ENCODE_FINISH`` (src/include/
encoding.h:1531, denc.h): a version byte, a compat floor, and a length
guard, so old daemons can skip fields they don't know and refuse
structures newer than they can safely read.  This framework's wire
format is JSON; the envelope carries the same three facts:

    {"v": <struct version>, "compat": <oldest reader that may decode>,
     "data": {...}}

``decode`` raises on ``compat`` above the reader's supported version
(the reference's buffer::malformed_input behavior) and delivers the
payload with the writer's version so readers can branch on it — the
ENCODE_START/DECODE_START contract, JSON-shaped.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Tuple


class MalformedInput(ValueError):
    pass


def encode(data: Dict[str, Any], version: int = 1,
           compat: int = 1) -> str:
    if compat > version:
        raise ValueError("compat cannot exceed version")
    return json.dumps({"v": version, "compat": compat, "data": data})


def decode(blob: str | bytes,
           supported: int = 1) -> Tuple[int, Dict[str, Any]]:
    """Returns (writer_version, payload); raises MalformedInput when
    the writer demands a newer reader than ``supported``."""
    try:
        env = json.loads(blob)
        v = int(env["v"])
        compat = int(env["compat"])
        data = env["data"]
    except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
        raise MalformedInput(f"bad envelope: {e}")
    if compat > supported:
        raise MalformedInput(
            f"structure requires decoder v{compat}, have v{supported}")
    return v, data


class Versioned:
    """Mixin: classes with to_dict/from_dict gain versioned wire forms.

    Subclasses set STRUCT_V/COMPAT_V and may override
    ``upgrade(writer_v, data)`` to migrate old payloads forward — the
    role of the per-version branches inside reference decode() bodies.
    """

    STRUCT_V = 1
    COMPAT_V = 1

    def encode_versioned(self) -> str:
        return encode(self.to_dict(), self.STRUCT_V, self.COMPAT_V)

    @classmethod
    def decode_versioned(cls, blob: str | bytes):
        v, data = decode(blob, supported=cls.STRUCT_V)
        data = cls.upgrade(v, data)
        return cls.from_dict(data)

    @classmethod
    def upgrade(cls, writer_v: int, data: Dict[str, Any]
                ) -> Dict[str, Any]:
        return data
