"""Versioned encode/decode envelopes — the denc/encoding.h seam.

The reference wraps every wire/disk structure in
``ENCODE_START(v, compat_v)`` / ``ENCODE_FINISH`` (src/include/
encoding.h:1531, denc.h): a version byte, a compat floor, and a length
guard, so old daemons can skip fields they don't know and refuse
structures newer than they can safely read.  This framework's wire
format is JSON; the envelope carries the same three facts:

    {"v": <struct version>, "compat": <oldest reader that may decode>,
     "data": {...}}

``decode`` raises on ``compat`` above the reader's supported version
(the reference's buffer::malformed_input behavior) and delivers the
payload with the writer's version so readers can branch on it — the
ENCODE_START/DECODE_START contract, JSON-shaped.

Every structure registered in ``analysis/wirecheck.py`` is
machine-checked for the five conformance properties (round-trip,
determinism, forward-compat, compat-floor refusal, mutation
robustness) and pinned by the committed corpus under
``tests/corpus/encodings/`` — the ceph-object-corpus role.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Tuple


class MalformedInput(ValueError):
    """A wire/disk blob this reader must refuse: truncated, tampered,
    future-compat, or semantically undecodable.  The buffer::
    malformed_input role — every decode seam raises THIS (never a raw
    KeyError/struct.error/assert), so transports and mounts can treat
    'bad bytes' as one clean protocol-error class."""


def encode(data: Dict[str, Any], version: int = 1,
           compat: int = 1) -> str:
    if compat > version:
        raise ValueError("compat cannot exceed version")
    return json.dumps({"v": version, "compat": compat, "data": data})


def decode(blob: str | bytes, supported: int = 1,
           struct: str = "structure") -> Tuple[int, Dict[str, Any]]:
    """Returns (writer_version, payload); raises MalformedInput when
    the writer demands a newer reader than ``supported``.  ``struct``
    names the structure in error messages — "which struct refused"
    is the first question every decode failure raises."""
    try:
        env = json.loads(blob)
        v = int(env["v"])
        compat = int(env["compat"])
        data = env["data"]
    except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
        raise MalformedInput(f"{struct}: bad envelope: {e}")
    if compat > supported:
        raise MalformedInput(
            f"{struct} (writer v{v}) requires decoder v{compat}, "
            f"have v{supported}")
    return v, data


def is_envelope(obj: Any) -> bool:
    """True when a parsed JSON value has the envelope shape."""
    return isinstance(obj, dict) and set(obj) == {"v", "compat", "data"}


def decode_any(blob: str | bytes, supported: int = 1,
               struct: str = "structure") -> Tuple[int, Any]:
    """Lenient decode for formats MIGRATED behind the envelope: blobs
    written before the migration are bare JSON values and decode as
    writer version 0, so archived v0 data (an old image header, a
    pre-envelope mon epoch file) keeps decoding forever — the
    ceph-object-corpus backward-readability contract."""
    try:
        parsed = json.loads(blob)
    except (TypeError, ValueError) as e:
        raise MalformedInput(f"{struct}: undecodable blob: {e}")
    if is_envelope(parsed):
        return decode(blob, supported=supported, struct=struct)
    return 0, parsed


class Versioned:
    """Mixin: classes with to_dict/from_dict gain versioned wire forms.

    Subclasses set STRUCT_V/COMPAT_V and may override
    ``upgrade(writer_v, data)`` to migrate old payloads forward — the
    role of the per-version branches inside reference decode() bodies.

    A payload that survives the envelope but breaks from_dict (a
    tampered field, a wrong type) is re-raised as MalformedInput
    naming the struct and versions: decoding hostile bytes must be a
    typed protocol error, never an uncaught KeyError.
    """

    STRUCT_V = 1
    COMPAT_V = 1

    def encode_versioned(self) -> str:
        return encode(self.to_dict(), self.STRUCT_V, self.COMPAT_V)

    @classmethod
    def decode_versioned(cls, blob: str | bytes):
        v, data = decode(blob, supported=cls.STRUCT_V,
                         struct=cls.__name__)
        try:
            data = cls.upgrade(v, data)
            return cls.from_dict(data)
        except MalformedInput:
            raise
        except (KeyError, TypeError, ValueError, IndexError,
                AttributeError) as e:
            raise MalformedInput(
                f"{cls.__name__} (writer v{v}, reader v"
                f"{cls.STRUCT_V}): bad payload: {e!r}")

    @classmethod
    def upgrade(cls, writer_v: int, data: Dict[str, Any]
                ) -> Dict[str, Any]:
        return data
