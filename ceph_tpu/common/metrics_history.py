"""Metrics history — per-daemon counter time-series in a bounded ring.

PR 2's telemetry plane exposes point-in-time counter snapshots; the
interesting failure modes of an EC data path (CPU saturation, batching
collapse, recovery interference) are only visible as *rates over
time*.  This module is the continuous half: every daemon samples its
merged perf state (its own ``PerfCountersCollection`` over the
process-global library counters — the same merge ``perf dump``
serves) into an in-memory ring at a configurable interval, and the
``dump_metrics_history`` admin command serves the ring with derived
rates and log2-histogram deltas computed at READ time — sampling
stays a cheap dict copy, no math on the hot path.

The mgr-internal MetricsHistory / ``ceph daemonperf`` role, turned
inward: ``ceph_tpu/tools/telemetry.py`` scrapes every daemon's ring
and merges them into one time-aligned cluster series.

Wired by ``Context.start_admin_socket()`` when
``metrics_history_interval`` > 0, stopped by ``Context.shutdown()``
(the sampler is one daemon thread; tests' thread-leak gate sees it
die with its context).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, List, Optional

from ..analysis.lockdep import make_lock
from ..analysis.racecheck import guarded_by
from . import device_metrics
from .perf_counters import PerfCountersCollection, collection


@guarded_by("metrics::history", "_ring",
            owned_by_thread=("sample_errors", "last_error"))
class MetricsHistory:
    def __init__(self, name: str,
                 perf: Optional[PerfCountersCollection] = None,
                 interval: float = 1.0, retention: int = 240):
        self.name = name
        self.interval = max(0.05, float(interval))
        self._perf = perf
        self._ring: Deque[Dict] = collections.deque(
            maxlen=max(2, int(retention)))
        self._lock = make_lock("metrics::history")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sample_errors = 0
        self.last_error: Optional[str] = None

    # -- sampling -----------------------------------------------------
    def sample(self) -> None:
        """One ring entry: wall + monotonic stamps, the merged perf
        dump, and the device-plane shape table.  The monotonic stamp
        is what rates divide by — wall time may step."""
        device_metrics.sample_memory()
        merged = dict(collection().dump())
        if self._perf is not None:
            merged.update(self._perf.dump())
        entry = {"ts": time.time(), "mono": time.monotonic(),
                 "perf": merged,
                 "shapes": device_metrics.shape_table()}
        with self._lock:
            self._ring.append(entry)

    def start(self) -> None:
        if self._thread is not None:
            return
        self.sample()  # the ring is never empty once started
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"metrics:{self.name}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sample()
            except Exception as e:
                # one bad sample (a logger torn down mid-dump) must
                # not kill the sampler — the ring skips a beat, but
                # never silently (the swallowed-run-loop lint class)
                self.sample_errors += 1
                self.last_error = repr(e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # -- read side ----------------------------------------------------
    def samples(self, last: Optional[int] = None) -> List[Dict]:
        with self._lock:
            out = list(self._ring)
        return out[-int(last):] if last else out

    def dump(self, last: Optional[int] = None) -> Dict:
        """The ``dump_metrics_history`` payload: raw samples plus the
        derived views (rates per changed counter, histogram bucket
        deltas first->last) computed here, at read time."""
        samples = self.samples(last)
        return {"name": self.name,
                "interval": self.interval,
                "retention": self._ring.maxlen,
                "n": len(samples),
                "samples": samples,
                "rates": derive_rates(samples),
                "hist_deltas": hist_deltas(samples)}

    def wire(self, admin_socket) -> None:
        admin_socket.register(
            "dump_metrics_history",
            lambda a: self.dump(last=a.get("last")),
            "counter time-series ring with derived rates "
            "(?last= limits samples)")


# -- derived views (shared with the cluster-side merge in
# tools/telemetry.py, and with tests recomputing them for the
# rates-consistent-with-deltas acceptance gate) ------------------------

def _numeric_items(perf: Dict) -> Dict[str, float]:
    """Flatten one sample's perf dump to {'logger.key': value} for
    plain numeric counters (avg pairs contribute their sum; hists are
    handled separately)."""
    out: Dict[str, float] = {}
    for logger, counters in (perf or {}).items():
        if not isinstance(counters, dict):
            continue
        for key, val in counters.items():
            if isinstance(val, (int, float)):
                out[f"{logger}.{key}"] = float(val)
            elif isinstance(val, dict) and "avgcount" in val:
                out[f"{logger}.{key}.sum"] = float(val.get("sum", 0))
                out[f"{logger}.{key}.count"] = float(
                    val.get("avgcount", 0))
    return out


def derive_rates(samples: List[Dict]) -> Dict[str, List[Dict]]:
    """Per-counter rate series between consecutive samples, only for
    counters that changed at least once (the unchanged majority would
    bury the signal).  Monotonic timestamps; negative deltas (a
    counter reset) clamp to 0.

    Ring-wrap audit: rates are derived at READ time from whatever the
    bounded ring currently retains — consecutive pairs of RETAINED
    samples only (``zip(samples, samples[1:])``).  Once the ring wraps
    past its retention, the oldest retained sample becomes the first
    pair's LEFT endpoint; its evicted predecessor is never consulted,
    so the first derived rate spans [oldest_retained,
    second_oldest_retained] — a real interval — rather than a phantom
    interval against a dropped sample.  Pinned by
    tests/test_observability.py::test_metrics_history_ring_wrap_rates.
    """
    if len(samples) < 2:
        return {}
    flats = [_numeric_items(s.get("perf", {})) for s in samples]
    changed = {k for a, b in zip(flats, flats[1:])
               for k in b if b.get(k) != a.get(k)}
    out: Dict[str, List[Dict]] = {k: [] for k in sorted(changed)}
    for (sa, fa), (sb, fb) in zip(zip(samples, flats),
                                  zip(samples[1:], flats[1:])):
        dt = max(1e-9, sb.get("mono", 0) - sa.get("mono", 0))
        for k in out:
            if k in fb and k in fa:
                out[k].append(
                    {"ts": sb.get("ts"),
                     "dt": round(dt, 6),
                     "rate": max(0.0, (fb[k] - fa[k]) / dt)})
    return out


def hist_deltas(samples: List[Dict]) -> Dict[str, Dict]:
    """First->last bucket deltas per histogram counter that moved —
    'what latencies did this window actually see'."""
    if len(samples) < 2:
        return {}
    first, lastp = samples[0].get("perf", {}), samples[-1].get(
        "perf", {})
    out: Dict[str, Dict] = {}
    for logger, counters in (lastp or {}).items():
        if not isinstance(counters, dict):
            continue
        for key, val in counters.items():
            if not (isinstance(val, dict) and "buckets" in val):
                continue
            prev = (first.get(logger) or {}).get(key) or {}
            pbuck = prev.get("buckets") or [0] * len(val["buckets"])
            delta = [max(0, b - a) for a, b in
                     zip(pbuck, val["buckets"])]
            if any(delta):
                out[f"{logger}.{key}"] = {
                    "buckets": delta, "min": val.get("min", 1e-6),
                    "count": sum(delta)}
    return out
