"""Object versions — the eversion_t (epoch, version) role.

One definition shared by writers (client), storers (osd_service), and
peering: zero-padded decimal fields so STRING comparison is version
comparison.  Any change here must change every comparer at once —
that's why there is exactly one copy.
"""

from __future__ import annotations

import time


def make_version(epoch: int) -> str:
    """Totally-ordered object version: map epoch + wall timestamp.
    All shards of one logical write share one version, so replicas
    agree on recency at peering time."""
    return f"{epoch:012d}.{time.time_ns():020d}"


NULL_VERSION = "0" * 12 + "." + "0" * 20


def bump(version: str) -> str:
    """The smallest version strictly greater than ``version`` (same
    epoch field, timestamp+1).  Lets a writer whose wall clock lags a
    stored version re-stamp PAST it instead of silently losing
    last-writer-wins — the read-your-writes repair for client clock
    skew."""
    epoch_s, ts_s = version.split(".")
    return f"{epoch_s}.{int(ts_s) + 1:020d}"
