"""Compressor plugins — the src/compressor registry re-expressed.

The reference's compressor mirrors the EC plugin design (plugin
registry + per-pool selection: zlib/zstd/lz4/snappy).  The framework
carries the registry with the codecs the Python runtime ships (zlib,
lzma, and the identity codec); additional codecs register through the
same factory seam.
"""

from __future__ import annotations

import lzma
import zlib
from typing import Callable, Dict, Tuple

_Codec = Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]

_REGISTRY: Dict[str, _Codec] = {}


def register(name: str, compress: Callable[[bytes], bytes],
             decompress: Callable[[bytes], bytes]) -> None:
    _REGISTRY[name] = (compress, decompress)


def plugins() -> list:
    return sorted(_REGISTRY)


class Compressor:
    def __init__(self, name: str):
        if name not in _REGISTRY:
            raise KeyError(f"no compressor {name!r}; have {plugins()}")
        self.name = name
        self._c, self._d = _REGISTRY[name]

    def compress(self, data: bytes) -> bytes:
        return self._c(data)

    def decompress(self, data: bytes) -> bytes:
        return self._d(data)


register("none", lambda b: b, lambda b: b)
register("zlib", lambda b: zlib.compress(b, 6), zlib.decompress)
register("lzma", lambda b: lzma.compress(b, preset=1),
         lzma.decompress)
