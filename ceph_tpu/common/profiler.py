"""In-process wallclock sampling profiler — folded stacks per role.

The reference ships a wallclock profiler that attaches to a live
daemon and emits collapsed stacks; here the daemons are threads in
one process, so the profiler samples ``sys._current_frames()`` from a
dedicated thread instead of ptrace.  Each sample walks every thread's
current stack and accumulates a folded-stack count keyed by *thread
role* — the pool prefix of the thread name (``msgr-dispatch:osd.1_3``
-> ``msgr-dispatch``, ``mclock-w0`` -> ``mclock-w``) — so the output
answers "which role burns wallclock where" without per-thread noise.

Operational shape, pinned by lint rule OBS002: the profiler is OFF by
default and only ever started from an admin-socket command (``profile
start|stop|dump`` on every daemon, wired in ``Context``) or from an
explicit bench hook — the lint rejects an unconditional
``profile_start`` call anywhere outside tests/bench.  Sampling uses a
*seeded* jittered interval (mean 1/hz, uniform in [0.5, 1.5]/hz) so
periodic work cannot hide between ticks yet runs stay reproducible,
and retention is bounded: at most ``max_stacks`` distinct folded
stacks (overflow lands in an explicit bucket) and ``max_seconds`` of
sampling before auto-stop, so a forgotten ``profile start`` cannot
grow without bound.

Dump format is flamegraph-collapsed text: ``role;frame;frame count``
per line, merged cluster-wide by ``tools/telemetry.py``'s
``flame`` report.
"""

from __future__ import annotations

import os
import random
import re
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..analysis.lockdep import make_lock

_ROLE_TRIM = re.compile(r"[-_]?\d+$")

# frame-label cache keyed by code object id — stable for the process
# lifetime and saves the basename/format work on every sample
_label_cache: Dict[int, str] = {}


def thread_role(name: str) -> str:
    """Pool role for a thread name: the prefix before the first
    ``:`` with any trailing worker index trimmed."""
    base = (name or "?").split(":", 1)[0]
    return _ROLE_TRIM.sub("", base) or base


def _frame_label(code) -> str:
    label = _label_cache.get(id(code))
    if label is None:
        label = f"{os.path.basename(code.co_filename)}:{code.co_name}"
        _label_cache[id(code)] = label
    return label


def _fold(frame, max_depth: int = 64) -> Tuple[str, ...]:
    """Root-first tuple of frame labels for one thread's stack."""
    rev: List[str] = []
    while frame is not None and len(rev) < max_depth:
        rev.append(_frame_label(frame.f_code))
        frame = frame.f_back
    rev.reverse()
    return tuple(rev)


class WallclockProfiler:
    """One sampler per daemon Context.  Thread-safe; start/stop are
    idempotent.  Method names are the lint-pinned surface: call sites
    of ``profile_start`` outside tests/bench must be conditional."""

    def __init__(self, hz: float = 100.0, max_seconds: float = 30.0,
                 max_stacks: int = 4096, seed: int = 0,
                 name: str = "prof"):
        self.hz = float(hz)
        self.max_seconds = float(max_seconds)
        self.max_stacks = int(max_stacks)
        self.name = name
        self._rng = random.Random(seed)
        self._lock = make_lock(f"profiler::{name}")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (role, folded stack) -> sample count
        self._stacks: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        # sampling memos: thread ident -> role (refreshed whenever an
        # unknown ident shows up), and ident -> [frame id, code id,
        # f_lasti, folded key, pending count] so a thread parked in a
        # wait() — the common case in a daemon pool — is not
        # re-folded every tick.  Hits only bump the pending count;
        # counts merge into _stacks on miss/dump, keeping the big
        # (role, stack)-tuple hashing off the per-tick hot path.
        self._roles: Dict[int, str] = {}
        self._memo: Dict[int, List] = {}
        self._samples = 0
        self._truncated = 0
        self._started_at = 0.0
        self._elapsed = 0.0
        # wallclock the sampler itself burned inside _sample — the
        # direct overhead meter (in a GIL-bound process the sampler's
        # GIL-holding share IS the throughput tax on the workload)
        self._self_s = 0.0

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def profile_start(self, hz: Optional[float] = None) -> bool:
        """Begin sampling (resets prior retention).  Returns False if
        already running."""
        with self._lock:
            if self.running:
                return False
            if hz:
                self.hz = float(hz)
            self._stacks.clear()
            self._roles.clear()
            self._memo.clear()
            self._samples = 0
            self._truncated = 0
            self._elapsed = 0.0
            self._self_s = 0.0
            self._stop.clear()
            self._started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name=f"wallclock-prof:{self.name}",
                daemon=True)
            self._thread.start()
            return True

    def profile_stop(self) -> bool:
        """Stop sampling; retained stacks stay dumpable."""
        t = self._thread
        if t is None:
            return False
        self._stop.set()
        t.join(timeout=2.0)
        with self._lock:
            self._thread = None
        return True

    def _run(self) -> None:
        own = threading.get_ident()
        deadline = self._started_at + self.max_seconds
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= deadline:
                break
            # thread_time, not perf_counter: CPU seconds this thread
            # actually burned.  Wallclock would also book intervals
            # where the sampler sat descheduled mid-_sample waiting
            # for the GIL — time the workload was running, not time
            # stolen from it.
            t0 = time.thread_time()
            self._sample(own)
            self._self_s += time.thread_time() - t0
            # seeded jitter: mean 1/hz, never synchronized with
            # periodic daemon work
            interval = (1.0 / max(self.hz, 1e-3)) * \
                (0.5 + self._rng.random())
            self._stop.wait(interval)
        with self._lock:
            self._elapsed = time.monotonic() - self._started_at

    def _sample(self, own_ident: int) -> None:
        frames = sys._current_frames()
        roles = self._roles
        if any(i not in roles for i in frames):
            # a thread we have not seen: rebuild the ident -> role
            # map (threading.enumerate + regex trim per thread is
            # ~30% of raw sample cost — pay it only on churn)
            self._roles = roles = {
                t.ident: thread_role(t.name)
                for t in threading.enumerate()}
        memo = self._memo
        with self._lock:
            self._samples += 1
            for ident, frame in frames.items():
                if ident == own_ident:
                    continue
                # a parked thread (blocked in a pool's wait()) keeps
                # the same top frame at the same instruction between
                # ticks — bump its pending count instead of
                # re-walking the stack.  id() reuse is disarmed by
                # also pinning the code object id and f_lasti; a
                # sampling profiler tolerates the residual
                # (astronomically rare) collision.
                hit = memo.get(ident)
                if hit is not None and hit[0] == id(frame) \
                        and hit[1] == id(frame.f_code) \
                        and hit[2] == frame.f_lasti:
                    hit[4] += 1
                    continue
                if hit is not None:
                    self._merge(hit)
                memo[ident] = [id(frame), id(frame.f_code),
                               frame.f_lasti,
                               (roles.get(ident, "?"), _fold(frame)),
                               1]

    def _merge(self, hit: List) -> None:
        """Fold one memo entry's pending count into the retained
        stacks (lock held), honoring the max_stacks bound."""
        n = hit[4]
        if n <= 0:
            return
        key = hit[3]
        if key not in self._stacks and \
                len(self._stacks) >= self.max_stacks:
            self._truncated += n
            key = (key[0], ("<overflow>",))
        self._stacks[key] = self._stacks.get(key, 0) + n
        hit[4] = 0

    def profile_dump(self) -> Dict:
        """{"running", "hz", "samples", "elapsed", "self_s",
        "truncated", "folded": ["role;frame;... count", ...]} —
        folded lines in flamegraph-collapsed format, highest count
        first; ``self_s`` is the wallclock the sampler itself spent
        walking stacks (the direct overhead meter)."""
        with self._lock:
            for hit in self._memo.values():
                self._merge(hit)
            elapsed = (time.monotonic() - self._started_at
                       if self.running else self._elapsed)
            folded = sorted(self._stacks.items(),
                            key=lambda kv: -kv[1])
            lines = [";".join((role,) + stack) + f" {count}"
                     for (role, stack), count in folded]
            return {
                "running": self.running,
                "hz": self.hz,
                "samples": self._samples,
                "elapsed": round(elapsed, 3),
                "self_s": round(self._self_s, 6),
                "truncated": self._truncated,
                "folded": lines,
            }


def merge_folded(dumps: Dict[str, Dict]) -> Dict[str, int]:
    """Merge per-daemon ``profile_dump`` outputs into one cluster
    folded-stack map (``daemon/role;frames`` -> count) for the
    telemetry flame report."""
    merged: Dict[str, int] = {}
    for daemon, dump in sorted(dumps.items()):
        for line in dump.get("folded", []):
            stack, _, count = line.rpartition(" ")
            try:
                n = int(count)
            except ValueError:
                continue
            key = f"{daemon}/{stack}"
            merged[key] = merged.get(key, 0) + n
    return merged


def render_flame(merged: Dict[str, int], width: int = 60,
                 top: int = 40) -> str:
    """Text flamegraph summary: top folded stacks by sample count
    with a proportional bar — the terminal stand-in for a flamegraph
    SVG (the folded lines themselves feed flamegraph.pl unchanged)."""
    total = sum(merged.values()) or 1
    lines = [f"cluster wallclock profile — {total} samples, "
             f"{len(merged)} distinct stacks (top {top})"]
    ranked = sorted(merged.items(), key=lambda kv: -kv[1])[:top]
    for stack, count in ranked:
        share = count / total
        bar = "#" * max(1, int(share * width))
        leaf = stack.rsplit(";", 1)[-1]
        lines.append(f"{share:>6.1%} {count:>7d} {bar:<{width//3}} "
                     f"{leaf}  [{stack}]")
    return "\n".join(lines)
