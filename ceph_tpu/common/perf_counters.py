"""Perf counters — per-daemon metrics with a process registry.

The role of src/common/perf_counters.{h,cc}: a ``PerfCountersBuilder``
declares typed counters (u64 gauge/counter, time, averages with
count+sum, histograms), daemons bump them on hot paths (cheap,
lock-per-instance), and the admin socket's ``perf dump`` serializes
every collection (perf_counters.h:63-141 / PerfCountersCollection).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..analysis.lockdep import make_lock

U64 = "u64"          # monotonically increasing counter
GAUGE = "gauge"      # settable level
TIME = "time"        # accumulated seconds
AVG = "avg"          # (count, sum) pair -> mean on dump
HISTOGRAM = "hist"   # fixed power-of-two bucket counts


class PerfCounters:
    def __init__(self, name: str):
        self.name = name
        self._types: Dict[str, str] = {}
        self._values: Dict[str, float] = {}
        self._avgs: Dict[str, Tuple[int, float]] = {}
        self._hists: Dict[str, List[int]] = {}
        self._hist_mins: Dict[str, float] = {}
        self._lock = make_lock("perf::counters")

    def _require(self, key: str, *allowed: str) -> str:
        """A typo'd key on a hot path must raise a clear error, not a
        bare KeyError deep inside an update."""
        t = self._types.get(key)
        assert t is not None, \
            f"perf counter {self.name!r} has no key {key!r}"
        assert t in allowed, \
            (f"perf counter {self.name}/{key} is {t}, not one of "
             f"{allowed}")
        return t

    # -- declaration (PerfCountersBuilder) ----------------------------
    def add_u64_counter(self, key: str, desc: str = "") -> None:
        self._types[key] = U64
        self._values[key] = 0

    def add_u64(self, key: str, desc: str = "") -> None:
        self._types[key] = GAUGE
        self._values[key] = 0

    def add_time(self, key: str, desc: str = "") -> None:
        self._types[key] = TIME
        self._values[key] = 0.0

    def add_u64_avg(self, key: str, desc: str = "") -> None:
        self._types[key] = AVG
        self._avgs[key] = (0, 0.0)

    def add_histogram(self, key: str, buckets: int = 32,
                      desc: str = "", min_value: float = 1e-6) -> None:
        """Log2 buckets anchored at ``min_value``: bucket 0 holds
        values <= min_value, bucket i holds (min*2^(i-1), min*2^i].
        The default floor of 1 µs makes sub-second LATENCIES resolve
        (the old ``int(value).bit_length()`` scheme collapsed every
        sub-second sample into bucket 0); byte-sized histograms pass
        ``min_value=1``."""
        self._types[key] = HISTOGRAM
        self._hists[key] = [0] * buckets
        self._hist_mins[key] = float(min_value)

    # -- updates ------------------------------------------------------
    def inc(self, key: str, amount: float = 1) -> None:
        self._require(key, U64, GAUGE, TIME)
        with self._lock:
            self._values[key] += amount

    def dec(self, key: str, amount: float = 1) -> None:
        self._require(key, GAUGE)
        with self._lock:
            self._values[key] -= amount

    def set(self, key: str, value: float) -> None:
        self._require(key, GAUGE, U64)
        with self._lock:
            self._values[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        self._require(key, TIME)
        with self._lock:
            self._values[key] += seconds

    def avg_add(self, key: str, value: float) -> None:
        self._require(key, AVG)
        with self._lock:
            n, s = self._avgs[key]
            self._avgs[key] = (n + 1, s + value)

    def hist_add(self, key: str, value: float) -> None:
        self._require(key, HISTOGRAM)
        hist = self._hists[key]
        lo = self._hist_mins[key]
        if value <= lo:
            bucket = 0
        else:
            bucket = min(len(hist) - 1,
                         1 + int(math.floor(math.log2(value / lo))))
        with self._lock:
            hist[bucket] += 1

    # -- dump ---------------------------------------------------------
    def dump(self) -> Dict:
        with self._lock:
            out: Dict = {}
            for key, t in self._types.items():
                if t == AVG:
                    n, s = self._avgs[key]
                    out[key] = {"avgcount": n, "sum": s,
                                "avg": (s / n) if n else 0.0}
                elif t == HISTOGRAM:
                    out[key] = {"buckets": list(self._hists[key]),
                                "min": self._hist_mins[key]}
                else:
                    out[key] = self._values[key]
            return out


class PerfCountersCollection:
    """Process-wide registry (PerfCountersCollectionImpl)."""

    def __init__(self):
        self._loggers: Dict[str, PerfCounters] = {}
        self._lock = make_lock("perf::collection")

    def add(self, counters: PerfCounters) -> None:
        with self._lock:
            self._loggers[counters.name] = counters

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def create(self, name: str) -> PerfCounters:
        pc = PerfCounters(name)
        self.add(pc)
        return pc

    def dump(self, logger: Optional[str] = None) -> Dict:
        """The `perf dump` admin-socket payload."""
        with self._lock:
            items = ({logger: self._loggers[logger]}
                     if logger else dict(self._loggers))
        return {name: pc.dump() for name, pc in items.items()}


_collection: Optional[PerfCountersCollection] = None


def collection() -> PerfCountersCollection:
    global _collection
    if _collection is None:
        _collection = PerfCountersCollection()
    return _collection
