"""Messenger — threaded TCP transport with typed JSON dispatch.

The Messenger/Dispatcher seam (src/msg/Messenger.h, Dispatcher.h,
AsyncMessenger.cc) for the host control plane.  Framing: 4-byte
big-endian length + JSON body (binary payloads travel hex-encoded —
control-plane sizes, not data-plane).  Each messenger owns an accept
thread and per-connection reader threads; ``send`` opens (and caches)
client connections and is fire-and-forget; ``call`` is send + wait for
a reply correlated by ``tid`` (the MOSDOp/reply pattern).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import uuid
from typing import Callable, Dict, Optional, Tuple

Addr = Tuple[str, int]
Handler = Callable[[Dict], Optional[Dict]]

# per-socket send locks: sendall() on a large frame loops, so two
# threads writing the same cached connection would interleave bytes
# and corrupt the framing
_send_locks: Dict[int, threading.Lock] = {}
_send_locks_guard = threading.Lock()


def _send_frame(sock: socket.socket, msg: Dict) -> None:
    body = json.dumps(msg).encode()
    with _send_locks_guard:
        lock = _send_locks.setdefault(id(sock), threading.Lock())
    with lock:
        sock.sendall(struct.pack(">I", len(body)) + body)


def _recv_frame(sock: socket.socket) -> Optional[Dict]:
    header = b""
    while len(header) < 4:
        got = sock.recv(4 - len(header))
        if not got:
            return None
        header += got
    (length,) = struct.unpack(">I", header)
    body = b""
    while len(body) < length:
        got = sock.recv(min(65536, length - len(body)))
        if not got:
            return None
        body += got
    return json.loads(body.decode())


class Messenger:
    def __init__(self, name: str, host: str = "127.0.0.1",
                 port: int = 0, keyring=None):
        self.name = name
        self.keyring = keyring  # cephx-style frame auth when set
        self._handlers: Dict[str, Handler] = {}
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)
        self._listener.settimeout(0.2)
        self.addr: Addr = self._listener.getsockname()
        self._running = False
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: Dict[Addr, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._pending: Dict[str, Dict] = {}
        self._waiting: set = set()  # tids with a live waiter
        self._pending_cv = threading.Condition()

    # -- dispatch ------------------------------------------------------
    def register(self, type_: str, handler: Handler) -> None:
        """Handler returns a reply dict (routed back by tid) or None."""
        self._handlers[type_] = handler

    def start(self) -> None:
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"msgr:{self.name}")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        with conn:
            while self._running:
                try:
                    msg = _recv_frame(conn)
                except (OSError, ValueError):
                    break  # closed or corrupt frame: drop the session
                if msg is None:
                    break
                self._dispatch(conn, msg)
        with _send_locks_guard:
            _send_locks.pop(id(conn), None)

    def _sign(self, msg: Dict) -> Dict:
        if self.keyring is not None:
            msg = dict(msg)
            msg["mac"] = self.keyring.sign(msg)
        return msg

    def _dispatch(self, conn: socket.socket, msg: Dict) -> None:
        if self.keyring is not None and not self.keyring.verify(msg):
            return  # unauthenticated frame: drop silently (cephx deny)
        type_ = msg.get("type", "")
        if type_ == "__reply__":
            with self._pending_cv:
                if msg["tid"] in self._waiting:  # drop stragglers
                    self._pending[msg["tid"]] = msg.get("payload", {})
                    self._pending_cv.notify_all()
            return
        handler = self._handlers.get(type_)
        if handler is None:
            reply = {"error": f"no handler for {type_!r}"}
        else:
            try:
                reply = handler(msg)
            except Exception as e:
                reply = {"error": str(e)}
        if msg.get("tid") is not None:
            try:
                _send_frame(conn, self._sign(
                    {"type": "__reply__", "tid": msg["tid"],
                     "payload": reply}))
            except OSError:
                pass

    # -- client side ---------------------------------------------------
    def _connect(self, addr: Addr) -> socket.socket:
        addr = tuple(addr)
        with self._conn_lock:
            sock = self._conns.get(addr)
            if sock is not None:
                return sock
            sock = socket.create_connection(addr, timeout=5)
            self._conns[addr] = sock
            threading.Thread(target=self._reader, args=(sock,),
                             daemon=True).start()
            return sock

    def _drop(self, addr: Addr) -> None:
        with self._conn_lock:
            sock = self._conns.pop(tuple(addr), None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def send(self, addr: Addr, msg: Dict) -> None:
        """Fire-and-forget; one silent reconnect attempt (lossy
        policy)."""
        msg = self._sign(msg)
        for _ in range(2):
            try:
                _send_frame(self._connect(addr), msg)
                return
            except OSError:
                self._drop(addr)

    def call(self, addr: Addr, msg: Dict,
             timeout: float = 10.0) -> Dict:
        """Request/response correlated by tid.  A timeout does NOT
        close the (shared) connection — other in-flight calls on the
        same peer keep their replies; a genuinely dead socket raises
        OSError on the next send and is reconnected there."""
        tid = uuid.uuid4().hex
        msg = self._sign(dict(msg, tid=tid, frm=self.name))
        deadline = time.monotonic() + timeout
        with self._pending_cv:
            self._waiting.add(tid)
        try:
            try:
                _send_frame(self._connect(addr), msg)
            except OSError:
                # stale cached connection (peer restarted): one fresh
                # reconnect before giving up
                self._drop(addr)
                _send_frame(self._connect(addr), msg)
            with self._pending_cv:
                while tid not in self._pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._pending_cv.wait(
                            timeout=min(0.5, remaining)):
                        if time.monotonic() >= deadline:
                            raise TimeoutError(
                                f"{self.name}: no reply from {addr} "
                                f"for {msg['type']}")
                return self._pending.pop(tid)
        except OSError:
            self._drop(addr)
            raise
        finally:
            with self._pending_cv:
                self._waiting.discard(tid)
                self._pending.pop(tid, None)

    def shutdown(self) -> None:
        self._running = False
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_lock:
            for sock in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()
