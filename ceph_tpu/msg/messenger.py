"""Messenger — threaded TCP transport with typed dispatch and
session-layer reliability.

The Messenger/Dispatcher seam (src/msg/Messenger.h, Dispatcher.h,
AsyncMessenger.cc) plus the ProtocolV2 session layer
(src/msg/async/ProtocolV2.cc).

Framing (the reference message's header/front/DATA segmentation,
src/msg/Message.h: payload vs data bufferlists; ProtocolV2 rev1
frames): one length word, a version byte, then a JSON control segment
and N RAW binary segments.  ``bytes`` values anywhere in a message
dict are lifted out of the control segment and travel as raw
attachments — zero hex/base64 inflation, no JSON escaping, exactly
like MOSDOp carrying its data payload outside the front segment.  The
control segment optionally zlib-compresses (wire compression role);
data segments never do (payload bytes are entropy-dense, and the
reference compresses per-policy, not always).

On top of it, LOSSLESS peers (daemon↔daemon — the reference's
CEPH_MSGR_POLICY_LOSSLESS) get sequence-numbered frames with
ack/replay semantics:

- every sequenced frame carries (_sess, _s); the receiver keeps
  in_seq per (peer, session) and a bounded reply cache, so a frame
  that arrives twice (retransmission after a dropped connection) is
  deduplicated and its original reply is resent — exactly-once
  handler execution per session, the reconnect/replay contract of
  ProtocolV2.cc (out_seq/in_seq + requeue_sent).
- the sender buffers unacked frames; a reconnect handshake
  (``__hello__``) learns the peer's in_seq and retransmits only the
  tail; explicit ``__ack__`` frames trim the buffer in steady state.
  A reader-thread death with unacked frames triggers a background
  resync so a dropped TCP connection mid-op-stream heals without
  waiting for the next application send.
- the HMAC (msg/auth.py) signs the body INCLUDING (_sess, _s), so a
  captured frame replayed verbatim is rejected by the in_seq check —
  the cephx nonce-binding role.
- LOSSY peers (clients) keep the old fire-and-forget behavior
  (CEPH_MSGR_POLICY_LOSSY: the application's map-retry loop owns
  recovery), but every receiver still deduplicates sequenced traffic.

Per-type byte throttles (``throttles={type: Throttle}``) bound memory
taken by in-flight messages of a type before dispatch — the
osd_client_message_size_cap role (ceph_osd.cc:582-588).
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import socket
import struct
import threading
import time
import uuid
import zlib
from typing import Callable, Dict, Optional, Tuple

from ..analysis import asyncheck
from ..analysis import faults
from ..analysis import watchdog
from ..analysis.asyncheck import nonblocking
from ..analysis.lockdep import make_lock, make_rlock
from ..analysis.racecheck import guarded_by, shared
from ..common import bufpool
from ..common import copytrack
from ..common.backoff import Backoff
from ..common.encoding import MalformedInput
from ..common.log import getLogger
from ..common.perf_counters import PerfCounters
from ..common.tracing import Tracer

Addr = Tuple[str, int]
Handler = Callable[[Dict], Optional[Dict]]

# per-socket writers: sendall() on a large frame loops, so two threads
# writing the same cached connection would interleave bytes and corrupt
# the framing.  Beyond mutual exclusion, writers COALESCE: frames for
# one socket queue behind the current sender, and whichever thread
# holds the writer lock flushes everything queued in ONE send — a
# primary fanning a write out no longer pays a syscall + lock
# round-trip per frame sharing a connection.
#
# Entries are reaped on conn death, hard close, AND send failure (the
# old per-socket lock table leaked one entry per reconnect cycle: a
# send racing reader death re-created the entry after the reader's
# exit had reaped it, and nothing ever removed it again).


class _SendOp:
    __slots__ = ("buf", "done", "error")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.done = threading.Event()
        self.error: Optional[OSError] = None


class _SockWriter:
    __slots__ = ("lock", "q")

    def __init__(self):
        self.lock = make_lock("msgr::send")
        self.q: "collections.deque[_SendOp]" = collections.deque()


# mutation-checked under racecheck: every writer-table insert/reap
# must hold the guard; the lock-free reads in _send/dump_messenger
# are the deliberate GIL-atomic idiom shared() leaves legal
_sock_writers: Dict[int, _SockWriter] = shared(
    {}, "msgr::send_guard", "msgr.sock_writers")
_sock_writers_guard = make_lock("msgr::send_guard")

# A send slower than this is socket backpressure (or an armed wire
# fault), not syscall cost: only those book send_stall_time, so an
# idle cluster's meter reads exactly zero and any nonzero value means
# the kernel buffer pushed back.
_STALL_MIN_S = 1e-3

# stateless reusable null context for the data-lane handler path (a
# data handler may legitimately block on fan-out; only the control
# lane carries the non-blocking contract)
_NULL_CTX = contextlib.nullcontext()


class _ConnStats:
    """Per-connection saturation books (the ms_async per-connection
    logger role): byte/frame volume, cumulative send-stall time, and
    dispatch wait/latency sums split by lane — the raw material of
    ``dump_messenger``.  Fields are bumped lock-free from reader,
    sender and pool-worker threads; a torn ``+=`` under the GIL can
    lose an individual sample, which telemetry tolerates (the same
    trade the reference's perf counters make on relaxed atomics)."""

    __slots__ = ("peer", "bytes_in", "bytes_out", "frames_in",
                 "frames_out", "sends", "send_stall_s", "send_stalls",
                 "q_depth_peak", "wait_ctl_s", "wait_ctl_n",
                 "wait_data_s", "wait_data_n", "lat_ctl_s",
                 "lat_ctl_n", "lat_data_s", "lat_data_n")

    def __init__(self, peer: str):
        self.peer = peer
        self.bytes_in = 0
        self.bytes_out = 0
        self.frames_in = 0
        self.frames_out = 0
        self.sends = 0
        self.send_stall_s = 0.0
        self.send_stalls = 0
        self.q_depth_peak = 0
        self.wait_ctl_s = 0.0
        self.wait_ctl_n = 0
        self.wait_data_s = 0.0
        self.wait_data_n = 0
        self.lat_ctl_s = 0.0
        self.lat_ctl_n = 0
        self.lat_data_s = 0.0
        self.lat_data_n = 0

    def dump(self) -> Dict:
        return {
            "peer": self.peer,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "frames_in": self.frames_in,
            "frames_out": self.frames_out,
            "sends": self.sends,
            "send_stall_s": round(self.send_stall_s, 6),
            "send_stalls": self.send_stalls,
            "queue_depth_peak": self.q_depth_peak,
            "dispatch_wait_ctl": {
                "n": self.wait_ctl_n,
                "avg_ms": round(1e3 * self.wait_ctl_s
                                / self.wait_ctl_n, 3)
                if self.wait_ctl_n else 0.0},
            "dispatch_wait_data": {
                "n": self.wait_data_n,
                "avg_ms": round(1e3 * self.wait_data_s
                                / self.wait_data_n, 3)
                if self.wait_data_n else 0.0},
            "dispatch_lat_ctl": {
                "n": self.lat_ctl_n,
                "avg_ms": round(1e3 * self.lat_ctl_s
                                / self.lat_ctl_n, 3)
                if self.lat_ctl_n else 0.0},
            "dispatch_lat_data": {
                "n": self.lat_data_n,
                "avg_ms": round(1e3 * self.lat_data_s
                                / self.lat_data_n, 3)
                if self.lat_data_n else 0.0},
        }


def _writer_for(sock) -> _SockWriter:
    with _sock_writers_guard:
        w = _sock_writers.get(id(sock))
        if w is None:
            w = _sock_writers[id(sock)] = _SockWriter()
        return w


def _reap_writer(sock) -> None:
    with _sock_writers_guard:
        _sock_writers.pop(id(sock), None)

_UNACKED_CAP = 512      # frames buffered per lossless peer session
_REPLY_CACHE_CAP = 128  # replies cached per remote session

# call-correlation tids: random per-process prefix + counter.  As
# unique as a uuid4 per call for correlation purposes, at ~1/6 the
# cost — tids are minted 3+ times per client op on the data path.
_tid_prefix = uuid.uuid4().hex[:12]
_tid_counter = itertools.count(1)


def _next_tid() -> str:
    return f"{_tid_prefix}{next(_tid_counter):x}"


# control segments beyond this compress on the wire (map payloads and
# other large JSON; raw data segments are never compressed)
_COMPRESS_OVER = 16 << 10
_FRAME_V = 2        # frame format version byte
_FL_ZLIB = 0x01     # control segment is zlib-compressed

_BLOB_KEY = "__frame_blob__"
_ESC_KEY = "__frame_esc__"

# blob-table sanity ceiling: nothing legitimate ships this many data
# segments in one frame, and a forged count must not allocate first
_MAX_BLOBS = 1 << 16

# decompression-bomb ceiling: a compressed control segment may expand
# to at most this much.  The largest legitimate control segment is a
# full-map JSON payload (a few MB at 10k OSDs — big maps travel as
# binary map_bin data segments anyway); a 1 KiB frame claiming 100 MiB
# of zeros is an attack on the receiver's memory, and the reference
# bounds inbound message memory the same way
# (osd_client_message_size_cap).  Module-level so tests can lower it.
MAX_DECOMPRESSED = 32 << 20


def _lift_blobs(obj, blobs: list):
    """Replace every bytes-like value with a data-segment reference —
    the front/data split of the reference's Message bufferlists.  A
    LITERAL single-key dict that collides with either wire sentinel is
    escaped so _restore_blobs hands it back verbatim instead of
    resolving it into an unrelated data segment.

    Blobs are kept as the caller's buffer-protocol object (bytes,
    bytearray, memoryview) — NOT copied: the frame is materialised in
    exactly one gathered join at send time (`_send_frame`), and the
    caller's buffer is only read while it blocks in the send."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        blobs.append(obj)
        return {_BLOB_KEY: len(blobs) - 1}
    if isinstance(obj, dict):
        if len(obj) == 1 and next(iter(obj)) in (_BLOB_KEY, _ESC_KEY):
            return {_ESC_KEY: {k: _lift_blobs(v, blobs)
                               for k, v in obj.items()}}
        return {k: _lift_blobs(v, blobs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_lift_blobs(v, blobs) for v in obj]
    return obj


def _restore_blobs(obj, blobs: list):
    if isinstance(obj, dict):
        if len(obj) == 1 and _BLOB_KEY in obj:
            idx = obj[_BLOB_KEY]
            if not isinstance(idx, int) or not 0 <= idx < len(blobs):
                raise MalformedInput(
                    f"blob index {idx!r} out of range "
                    f"(frame has {len(blobs)})")
            return blobs[idx]
        if len(obj) == 1 and _ESC_KEY in obj:
            inner = obj[_ESC_KEY]
            if not isinstance(inner, dict):
                raise MalformedInput("malformed sentinel escape")
            return {k: _restore_blobs(v, blobs)
                    for k, v in inner.items()}
        return {k: _restore_blobs(v, blobs) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_restore_blobs(v, blobs) for v in obj]
    return obj


def _materialize_views(obj, pc=None, site: str = "recv"):
    """Deep-copy every memoryview leaf to bytes — the DELIBERATE copy
    for data that outlives its pooled recv segment (a reply payload
    handed to a waiting caller, a cached reply that a retransmission
    may resend seconds later).  Booked per leaf at the given ledger
    site; anything without views passes through untouched."""
    if isinstance(obj, memoryview):
        b = bytes(obj)  # copy-ok: stabilizing a view past its segment
        if pc is not None:
            copytrack.book_pc(pc, site, len(b), copies=1)
        return b
    if isinstance(obj, dict):
        return {k: _materialize_views(v, pc, site)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_materialize_views(v, pc, site) for v in obj]
    return obj


def encode_frame_parts(msg: Dict, keyring=None):
    """The pure frame codec, encode half, as a GATHER LIST: header +
    JSON control segment + blob table, with every data segment still
    the caller's buffer (no per-blob copy).  Returns (parts, nbytes);
    the transport joins the list exactly once at send time — the one
    deliberate, booked send-side materialisation."""
    blobs: list = []
    jmsg = _lift_blobs(msg, blobs)
    if keyring is not None:
        jmsg.pop("mac", None)
        jmsg["mac"] = keyring.sign(jmsg, blobs)
    body = json.dumps(jmsg).encode()  # wire-ok: the frame codec seam
    flags = 0
    if len(body) > _COMPRESS_OVER:
        body = zlib.compress(body, 1)
        flags |= _FL_ZLIB
    parts = [struct.pack("<BBI", _FRAME_V, flags, len(body)), body,
             struct.pack("<I", len(blobs))]
    nbytes = 10 + len(body)
    for b in blobs:
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
        nbytes += 4 + len(b)
    return parts, nbytes


def encode_frame(msg: Dict, keyring=None) -> bytes:
    """The pure frame codec, encode half (the wirecheck-registered
    seam): header + JSON control segment + blob table.  The outer
    length word is the transport's, added at send time."""
    parts, _n = encode_frame_parts(msg, keyring)
    return b"".join(parts)


def decode_frame(payload) -> Tuple[Dict, list]:
    """The pure frame codec, decode half.  Returns (msg, blobs);
    ``msg`` still holds data-segment references (the dispatcher
    restores them after MAC verification).  ``payload`` may be bytes
    or a memoryview over a pooled recv segment — data segments come
    back as ZERO-COPY slices of it (views are only valid while the
    segment is held; anything outliving the frame copies deliberately
    via ``_materialize_views``).  Every length field is bounds-checked
    against the frame, every parse failure raises MalformedInput: a
    truncated, forged, or compression-bomb frame must be a clean
    protocol error, never an uncaught struct.error (or an unbounded
    allocation) that kills the reader thread with its cleanup
    skipped."""
    if len(payload) < 6:
        raise MalformedInput(
            f"frame too short ({len(payload)} bytes)")
    ver, flags, jlen = struct.unpack_from("<BBI", payload, 0)
    if ver != _FRAME_V:
        # the frame-format compat floor: a peer speaking a newer
        # framing must be refused, not misparsed
        raise MalformedInput(f"unknown frame version {ver}, "
                             f"have v{_FRAME_V}")
    pos = 6
    if pos + jlen + 4 > len(payload):
        raise MalformedInput("truncated control segment")
    body = payload[pos:pos + jlen]
    pos += jlen
    if flags & _FL_ZLIB:
        d = zlib.decompressobj()
        try:
            body = d.decompress(body, MAX_DECOMPRESSED)
        except zlib.error as e:
            raise MalformedInput(f"bad compressed control: {e}")
        if d.unconsumed_tail or not d.eof:
            raise MalformedInput(
                f"control segment decompresses past the "
                f"{MAX_DECOMPRESSED}-byte cap")
    (nblobs,) = struct.unpack_from("<I", payload, pos)
    pos += 4
    if nblobs > _MAX_BLOBS or nblobs * 4 > len(payload) - pos:
        raise MalformedInput(f"blob table oversized ({nblobs} entries "
                             f"in {len(payload) - pos} bytes)")
    blobs = []
    for _ in range(nblobs):
        if pos + 4 > len(payload):
            raise MalformedInput("truncated blob table")
        (blen,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        if pos + blen > len(payload):
            raise MalformedInput("truncated blob")
        blobs.append(payload[pos:pos + blen])
        pos += blen
    if isinstance(body, memoryview):
        # copy-ok: control segment only — json needs a bytes object;
        # the data segments above stay views of the pooled payload
        body = bytes(body)
    try:
        msg = json.loads(body.decode())  # wire-ok: the frame codec seam
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise MalformedInput(f"undecodable control segment: {e}")
    if not isinstance(msg, dict):
        raise MalformedInput(
            f"control segment is {type(msg).__name__}, not an object")
    return msg, blobs


_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


def _sendmsg_all(sock: socket.socket, parts: list) -> None:
    """Scatter-gather send of the whole parts list (the writev role)
    with partial-send continuation — the data segments go from the
    caller's buffers straight to the kernel, never joined in
    userspace."""
    views = [memoryview(p) for p in parts]
    while views:
        n = sock.sendmsg(views)
        while views and n >= len(views[0]):
            n -= len(views[0])
            views.pop(0)
        if views and n:
            views[0] = views[0][n:]


def _send_frame(sock: socket.socket, msg: Dict, keyring=None,
                mutate=None) -> Tuple[int, int]:
    """Queue the frame on the socket's writer and flush — coalescing
    with whatever else is queued — as the writer-lock holder.  Returns
    ``(wire_size, joined)``: the wire size (header + payload) for the
    byte counters, and how many bytes were actually materialised in a
    userspace join (0 on the gathered fast path — the caller books
    that at the "send" ledger site).  Raises the send failure on the
    CALLER's thread even when another thread's flush carried (and
    failed) this frame.

    ``mutate`` (fault injection only) post-processes the framed bytes
    — flipping or truncating them — INSIDE the writer path, so the
    damaged frame still serializes correctly against coalesced
    writers instead of interleaving mid-batch."""
    parts, plen = encode_frame_parts(msg, keyring)
    parts.insert(0, struct.pack(">I", plen))
    buf = None
    if mutate is not None:
        # fault injection needs the contiguous frame to damage it
        buf = mutate(b"".join(parts))
    elif not _HAS_SENDMSG:
        buf = b"".join(parts)
    w = _writer_for(sock)
    # uncontended fast path: writer idle, nothing queued — gathered
    # sendmsg straight from the caller's buffers, no join at all (the
    # common case; the coalescing machinery below only engages under
    # write contention)
    if not w.q and w.lock.acquire(blocking=False):
        fast = False
        try:
            if not w.q:
                fast = True
                if buf is not None:
                    sock.sendall(buf)
                else:
                    _sendmsg_all(sock, parts)
        except OSError:
            _reap_writer(sock)
            raise
        finally:
            w.lock.release()
        if fast:
            return plen + 4, len(buf) if buf is not None else 0
    # contended path: the frame joins once so the flush-holder can
    # batch it with its queue neighbours in one send
    if buf is None:
        buf = b"".join(parts)
    op = _SendOp(buf)
    w.q.append(op)  # deque.append is atomic; order = send order
    while not op.done.is_set():
        if not w.lock.acquire(timeout=0.05):
            continue
        try:
            while not op.done.is_set():
                batch = []
                try:
                    while True:
                        batch.append(w.q.popleft())
                except IndexError:
                    pass
                if not batch:
                    break
                err: Optional[OSError] = None
                try:
                    # ONE gathered send for the whole batch (the
                    # writev role): the dominant cost of small frames
                    # is per-send syscall + wakeup, not bytes
                    sock.sendall(b"".join(o.buf for o in batch))
                except OSError as e:
                    err = e
                for o in batch:
                    o.error = err
                    o.done.set()
        finally:
            w.lock.release()
    if op.error is not None:
        _reap_writer(sock)  # dead socket: never strand its entry
        raise op.error
    return plen + 4, len(buf)


def _flip_control_byte(buf: bytes) -> bytes:
    """Fault-injection mutation (msgr.corrupt_frame): XOR the first
    byte of the frame's control segment.  The control segment is the
    only region decode_frame ALWAYS integrity-checks (JSON parse /
    zlib inflate) — a flipped blob byte would pass silently and
    corrupt stored data, which models a disk fault, not a wire one —
    so this is guaranteed to surface as MalformedInput + session
    drop at the receiver."""
    # layout: [4B outer length][<BBI header = 6B][control body]...
    pos = 4 + 6
    if len(buf) <= pos:
        return buf
    out = bytearray(buf)
    out[pos] ^= 0xFF
    return out  # bytearray: sendall/join take it without another copy


def _truncate_frame(buf: bytes) -> bytes:
    """Fault-injection mutation (msgr.close_mid_frame): keep only the
    first half of the framed bytes — the receiver blocks on the
    remainder until the injected close EOFs it."""
    return buf[:max(4, len(buf) // 2)]


def _recv_into(sock: socket.socket, view: memoryview) -> bool:
    """Fill ``view`` from the socket; False on EOF.  recv_into a
    caller-owned view: a 64 KiB data frame arrives in a few segments
    and neither concatenates prefixes nor allocates per segment."""
    pos = 0
    n = len(view)
    while pos < n:
        got = sock.recv_into(view[pos:])
        if not got:
            return False
        pos += got
    return True


def _recv_exact(sock: socket.socket, n: int):
    """Preallocated recv_into (header words and tests)."""
    buf = bytearray(n)
    if not _recv_into(sock, memoryview(buf)):
        return None
    return buf


def _recv_frame(sock: socket.socket):
    """Returns (msg, blobs, nbytes, seg) or None on EOF; parse errors
    surface as MalformedInput from the codec and drop the session.

    The payload lands in a pooled segment (``seg``) via recv_into —
    the ONE recv-side materialisation of the frame — and ``blobs`` are
    zero-copy views into it.  Ownership of the segment (refcount 1)
    passes to the caller on success; EOF and parse errors release it
    here."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    seg = bufpool.acquire(length, tag="msgr.recv")
    try:
        if not _recv_into(sock, seg.writable()):
            seg.release()
            return None
        msg, blobs = decode_frame(seg.view())
    except BaseException:
        seg.release()
        raise
    return msg, blobs, length, seg


class _OutSession:
    """Sender-side lossless state for one peer address."""

    def __init__(self):
        self.lock = make_rlock("msgr::out_session")  # serializes seq
        # assignment, handshake, and transmission → frames hit the
        # wire in order
        # buf_lock guards ONLY the unacked buffer: acks arrive on
        # reader threads and must trim without waiting on a handshake
        # in progress (which itself waits on that reader — deadlock)
        self.buf_lock = make_lock("msgr::out_buf")
        self.out_seq = 0
        self.unacked: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()
        self.synced = False  # handshake done on the current conn
        # tids of calls in flight on this session (guarded by
        # buf_lock): when the background resync gives the peer up,
        # these waiters are failed IMMEDIATELY instead of burning
        # their full timeout against a dead daemon — the stall that
        # held a primary's PG lock for 10s per push during thrash
        self.waiters: set = set()

    def trim(self, upto: int) -> None:
        """Transport-level ack: drops fire-and-forget frames only.  A
        frame still waiting for its REPLY stays buffered even though
        the peer received it — the reply may have died with the old
        connection, and only the retransmission (deduped server-side,
        cached reply resent) can recover it.  call() completes those
        via complete()."""
        with self.buf_lock:
            for s in list(self.unacked):
                if s > upto:
                    break
                frame, needs_reply = self.unacked[s]
                if not needs_reply:
                    del self.unacked[s]

    def complete(self, seq: int) -> None:
        with self.buf_lock:
            self.unacked.pop(seq, None)

    def buffer(self, seq: int, frame: Dict,
               needs_reply: bool) -> None:
        with self.buf_lock:
            self.unacked[seq] = (frame, needs_reply)
            while len(self.unacked) > _UNACKED_CAP:
                self.unacked.popitem(last=False)  # degrade to lossy

    def pending(self):
        with self.buf_lock:
            return [f for f, _nr in self.unacked.values()]


class _InSession:
    """Receiver-side dedup state for one remote (name, session).

    ``fifo``/``draining`` implement the per-session serial dispatch
    lane: sequenced lossless frames from one peer session execute in
    arrival order (one lane worker at a time) while different sessions
    still share the dispatch pool concurrently — the reference's
    per-connection DispatchQueue ordering, which the quorum layer
    needs (mon_accept(v+1) must not overtake mon_commit(v))."""

    def __init__(self):
        self.in_seq = 0
        self.replies: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()
        self.fifo: "collections.deque" = collections.deque()
        self.draining = False

    def cache_reply(self, seq: int, frame: Dict) -> None:
        self.replies[seq] = frame
        while len(self.replies) > _REPLY_CACHE_CAP:
            self.replies.popitem(last=False)


@guarded_by("msgr::conn", "_conns", "_accepted", "_conn_waiters")
@guarded_by("msgr::pending", "_pending", "_waiters")
class Messenger:
    def __init__(self, name: str, host: str = "127.0.0.1",
                 port: int = 0, keyring=None, lossless: bool = False,
                 throttles: Optional[Dict[str, object]] = None,
                 tracer: Optional[Tracer] = None, perf=None):
        self.name = name
        self.log = getLogger("msgr")
        self.keyring = keyring  # cephx-style frame auth when set
        self.lossless = lossless
        # the tracing plane: daemons pass their context's tracer so
        # transport spans nest under service spans; a standalone
        # messenger (CLI, tests) gets its own
        self.tracer = tracer if tracer is not None else Tracer(
            f"msgr.{name}")
        # wire + dispatch metrics; registered into the daemon's
        # collection when one is passed (so `perf dump` serves them),
        # else standalone
        self.pc = perf.create(f"msgr.{name}") if perf is not None \
            else PerfCounters(f"msgr.{name}")
        for key in ("bytes_in", "bytes_out", "frames_in",
                    "frames_out"):
            self.pc.add_u64_counter(key)
        # receipt -> handler completion (queue wait + execution)
        self.pc.add_histogram("dispatch_lat")
        self.pc.add_time("dispatch_time")
        # the saturation plane: wall time _send spent stalled against
        # socket backpressure (only sends past _STALL_MIN_S book, so
        # an unloaded wire reads 0), the send-queue depth seen per
        # send, and the dispatch wait/latency histograms split by
        # lane — what dump_messenger / `telemetry net` read
        self.pc.add_time("send_stall_time")
        self.pc.add_u64_counter("send_stalls")
        self.pc.add_histogram("send_queue_depth", min_value=1.0)
        self.pc.add_histogram("dispatch_wait_ctl")
        self.pc.add_histogram("dispatch_wait_data")
        self.pc.add_histogram("dispatch_lat_ctl")
        self.pc.add_histogram("dispatch_lat_data")
        # id(sock) -> _ConnStats, created on first traffic, reaped
        # with the reader (dict ops are GIL-atomic; no lock)
        self._conn_stats: Dict[int, _ConnStats] = {}
        # the byte-copy ledger (common/copytrack.py): recv/send copy
        # accounting books into the daemon's obs.copy counters when a
        # collection was passed, else the process-global ones
        self._copy_pc = copytrack.ledger(perf)
        self.session_id = uuid.uuid4().hex[:16]
        self.throttles = throttles or {}
        self._handlers: Dict[str, Handler] = {}
        self._ordered: set = set()  # types on the serial lane
        self._control: set = set()  # types on the control lane
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)
        self._listener.settimeout(0.2)
        self.addr: Addr = self._listener.getsockname()
        self._running = False
        self._shut = False  # terminal: no reconnects past shutdown()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: Dict[Addr, socket.socket] = {}
        # accept-side sockets, so shutdown can close them and their
        # reader threads exit promptly instead of lingering blocked in
        # recv until the remote end dies (cross-test thread leakage)
        self._accepted: set = set()
        self._conn_lock = make_lock("msgr::conn")
        self._out: Dict[Addr, _OutSession] = {}
        self._in: Dict[Tuple[str, str], _InSession] = {}
        self._in_lock = make_lock("msgr::in")
        self._pending: Dict[str, Dict] = {}
        # tid -> per-call Event: a reply wakes exactly ITS caller.
        # (The old shared Condition notify_all'd every in-flight
        # caller per reply — O(window) wakeups per op, which made
        # throughput DROP as the aio window grew.)
        self._waiters: Dict[str, threading.Event] = {}
        # id(conn) -> tids of CONN-BOUND calls (lossy calls and the
        # __hello__ handshake — no session replay behind them): when
        # the conn's reader exits these fail immediately instead of
        # burning their full timeout against a dead peer.  A client
        # put() once waited 20s on an OSD killed mid-call, and a
        # resync handshake waited 5s holding the session lock.
        self._conn_waiters: Dict[int, set] = {}
        self._pending_lock = make_lock("msgr::pending")
        # lazy dispatch pools (DispatchQueue role); created on first
        # inbound op so pure clients never spawn them.  Two lanes: the
        # wide op pool, and a small CONTROL pool reserved for
        # latency-critical types (heartbeats, map/peering pushes) so a
        # burst of store ops occupying every op worker can never
        # head-of-line-block failure detection — the reference's
        # dedicated heartbeat messengers + mgr/mon priority queues.
        self._pool = None
        self._ctl_pool = None
        self._pool_lock = make_lock("msgr::pool")

    # -- dispatch ------------------------------------------------------
    def register(self, type_: str, handler: Handler,
                 ordered: bool = False,
                 control: bool = False) -> None:
        """Handler returns a reply dict (routed back by tid) or None.

        ``ordered=True`` puts the type on the per-session serial lane:
        sequenced frames of ordered types from one peer session run in
        arrival order relative to EACH OTHER (the reference's ordered
        DispatchQueue), which state machines like the quorum need —
        mon_accept(v+1) must not overtake mon_commit(v).  Unordered
        types keep full fast-dispatch parallelism (the reference's
        ms_fast_dispatch), so a store op blocking in the scheduler
        can never head-of-line-block a session's control traffic.

        ``control=True`` additionally dispatches the type on the
        dedicated control pool: a latency-critical frame (a heartbeat,
        a map push, a peering probe) must never queue behind a burst
        of shard writes that has every op worker blocked in the
        object store.  Composes with ``ordered`` (the serial lane
        drains on the control pool)."""
        self._handlers[type_] = handler
        if ordered:
            self._ordered.add(type_)
        if control:
            self._control.add(type_)

    def start(self) -> None:
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"msgr:{self.name}")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._listener.accept()
                # ms_tcp_nodelay (on by default in the reference):
                # Nagle + delayed ACK turns the request/ack/reply
                # triple into double-digit-ms stalls
                conn.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except socket.timeout:
                continue
            except OSError:
                break
            with self._conn_lock:
                self._accepted.add(conn)
            threading.Thread(target=self._reader, args=(conn, None),
                             daemon=True,
                             name=f"msgr-rd:{self.name}").start()

    def _reader(self, conn: socket.socket, addr: Optional[Addr]) -> None:
        """``addr`` set = a client-initiated connection we own; its
        death with unacked frames triggers a background resync."""
        with conn:
            while self._running:
                try:
                    got = _recv_frame(conn)
                except (OSError, ValueError, struct.error,
                        zlib.error):
                    break  # closed or corrupt frame: drop the session
                if got is None:
                    break
                msg, blobs, nbytes, seg = got
                self.pc.inc("bytes_in", nbytes + 4)
                self.pc.inc("frames_in")
                cs = self._conn_stat(conn)
                cs.bytes_in += nbytes + 4
                cs.frames_in += 1
                # recv copies: ONE recv_into fill of the pooled
                # segment per frame — the data-segment slices are
                # views into it now, so the old per-blob
                # re-materialisation is gone; anything outliving the
                # frame books its own copy via _materialize_views
                copytrack.book_pc(self._copy_pc, "recv", nbytes,
                                  copies=1)
                try:
                    self._dispatch(conn, msg, blobs, nbytes, seg)
                except Exception as e:
                    # a poisoned frame (bad blob reference, malformed
                    # control fields) drops THAT frame; the reader —
                    # and with it the session's resync/cleanup path —
                    # must survive it
                    self.log.derr(f"{self.name}: dropping bad frame "
                                  f"({msg.get('type')!r}): {e!r}")
        _reap_writer(conn)
        self._conn_stats.pop(id(conn), None)
        with self._conn_lock:
            self._accepted.discard(conn)
            tids = self._conn_waiters.pop(id(conn), set())
        if tids:
            with self._pending_lock:
                for tid in tids:
                    ev = self._waiters.get(tid)
                    if ev is not None and tid not in self._pending:
                        self._pending[tid] = {
                            "__session_dead__": "connection lost"}
                        ev.set()
        if addr is not None:
            self._on_conn_death(addr, conn)

    def _on_conn_death(self, addr: Addr, conn) -> None:
        with self._conn_lock:
            if self._conns.get(addr) is conn:
                self._conns.pop(addr, None)
        sess = self._out.get(addr)
        if sess is not None:
            with sess.lock:
                sess.synced = False
                dirty = bool(sess.unacked)
            if dirty and self._running:
                threading.Thread(target=self._resync, args=(addr,),
                                 daemon=True).start()

    def _resync(self, addr: Addr) -> None:
        """Reconnect + replay after a dropped lossless connection.
        When every attempt fails the peer is presumed dead: calls
        still waiting on this session fail NOW (their frames stay
        buffered — a later reconnect replays them and dedup keeps
        exactly-once execution)."""
        bo = Backoff(base=0.05, cap=0.5, deadline=3.0)
        for _ in range(8):
            if not self._running:
                return
            try:
                with self._out[addr].lock:
                    self._ensure_synced(addr)
                return
            except (OSError, TimeoutError):
                if not bo.sleep():
                    break
        self._fail_waiters(addr, "peer unreachable after resync")

    def _fail_waiters(self, addr: Addr, why: str) -> None:
        sess = self._out.get(tuple(addr))
        if sess is None:
            return
        with sess.buf_lock:
            tids = list(sess.waiters)
            sess.waiters.clear()
        if not tids:
            return
        with self._pending_lock:
            for tid in tids:
                ev = self._waiters.get(tid)
                if ev is not None and tid not in self._pending:
                    self._pending[tid] = {"__session_dead__": why}
                    ev.set()

    def _conn_stat(self, conn: socket.socket) -> _ConnStats:
        cs = self._conn_stats.get(id(conn))
        if cs is None:
            try:
                peer = "%s:%d" % conn.getpeername()[:2]
            except OSError:
                peer = "?"
            cs = self._conn_stats.setdefault(id(conn),
                                             _ConnStats(peer))
        return cs

    def _send(self, conn: socket.socket, msg: Dict) -> None:
        """Sign-at-wire-time send: frames are stored/buffered unsigned
        (and may hold raw ``bytes`` values); the MAC is computed over
        the lifted control segment + data-segment digests."""
        # stall clock starts BEFORE the fault block: an armed
        # msgr.delay_frame models a slow wire, and the whole point of
        # the meter is that slow wires surface as send stall
        t0 = time.monotonic()
        mutate = None
        close_after = False
        if faults._ACTIVE:  # one bool test when nothing is armed
            if faults.fires("msgr.drop_frame", self.name):
                # a TCP stream never silently loses a frame — wire
                # loss manifests as a dead connection (the `ms inject
                # socket failures` model); the lossless session's
                # unacked buffer replays through the reconnect
                self._hard_close(conn)
                return
            faults.sleep_if("msgr.delay_frame", self.name)
            if faults.fires("msgr.corrupt_frame", self.name):
                mutate = _flip_control_byte
            elif faults.fires("msgr.close_mid_frame", self.name):
                mutate = _truncate_frame
                close_after = True
        w = _sock_writers.get(id(conn))
        depth = len(w.q) if w is not None else 0
        n, joined = _send_frame(conn, msg, self.keyring,
                                mutate=mutate)
        self.pc.inc("bytes_out", n)
        self.pc.inc("frames_out")
        cs = self._conn_stat(conn)
        cs.bytes_out += n
        cs.frames_out += 1
        cs.sends += 1
        if depth:
            self.pc.hist_add("send_queue_depth", depth)
            if depth > cs.q_depth_peak:
                cs.q_depth_peak = depth
        stall = time.monotonic() - t0
        if stall >= _STALL_MIN_S:
            self.pc.tinc("send_stall_time", stall)
            self.pc.inc("send_stalls")
            cs.send_stall_s += stall
            cs.send_stalls += 1
        # send copies: the uncontended path gathers the frame straight
        # from the caller's buffers (sendmsg scatter-gather — zero
        # userspace join); only the contended/fault paths materialise
        # the frame, and exactly that join is booked
        if joined:
            copytrack.book_pc(self._copy_pc, "send", joined,
                              copies=1)
        if faults._ACTIVE and not close_after and \
                faults.fires("msgr.dup_frame", self.name):
            # receiver-side seq dedup (or reply-tid idempotence) must
            # absorb the retransmission
            _send_frame(conn, msg, self.keyring)
        if close_after:
            self._hard_close(conn)

    @nonblocking
    def _dispatch(self, conn: socket.socket, msg: Dict, blobs: list,
                  nbytes: int, seg=None) -> None:
        """Owns ``seg`` — the pooled recv segment every blob view in
        this frame lives in.  ``owned`` tracks the obligation: early
        control paths fall through to the release in ``finally``; the
        handler paths transfer ownership (the fifo entry / the pool
        task releases after the handler returns — views in ``msg``
        are valid exactly that long).  A parse or verify failure
        releases before the error reaches the reader's
        drop-bad-frame log."""
        owned = seg
        try:
            t_rx = time.monotonic()  # dispatch_lat anchor: receipt
            if self.keyring is not None and \
                    not self.keyring.verify(msg, blobs):
                return  # unauthenticated frame: drop (cephx deny)
            msg = _restore_blobs(msg, blobs)
            type_ = msg.get("type", "")
            if type_ == "__reply__":
                # the waiting caller keeps the payload past this
                # frame: stabilize its views NOW (the one deliberate
                # recv-side copy a read reply pays), then the
                # segment can recycle
                payload = _materialize_views(msg.get("payload", {}),
                                             self._copy_pc, "recv")
                with self._pending_lock:
                    ev = self._waiters.get(msg["tid"])  # drop
                    # stragglers
                    if ev is not None:
                        self._pending[msg["tid"]] = payload
                        ev.set()
                return
            if type_ == "__ack__":
                sess = self._out.get(tuple(msg["addr"]))
                if sess is not None and \
                        msg.get("sess") == self.session_id:
                    sess.trim(int(msg["in_seq"]))  # buf_lock only:
                    # an ack must never wait behind a handshake on
                    # this session
                return
            if type_ == "__hello__":
                key = (msg.get("frm", ""), msg.get("sess", ""))
                with self._in_lock:
                    ins = self._in.setdefault(key, _InSession())
                # the handshake reply moves OFF the reader thread
                # (asyncheck BLOCK001): _reply -> _send -> sendall
                # can stall on a backpressured peer socket, and this
                # thread is the one draining EVERY frame on the
                # connection — a wedged hello reply froze acks,
                # replies and dispatch behind it.  The in_seq
                # snapshot is taken above, so a delayed send changes
                # nothing the peer can observe.
                self._pool_submit(self._reply, conn, msg,
                                  {"in_seq": ins.in_seq, "ok": True},
                                  control=True)
                return

            seq = msg.get("_s")
            ins = None
            if seq is not None:
                key = (msg.get("frm", ""), msg.get("_sess", ""))
                with self._in_lock:
                    ins = self._in.setdefault(key, _InSession())
                    dup = seq <= ins.in_seq
                    if not dup:
                        ins.in_seq = seq
                if dup:
                    # duplicate (retransmission or replayed capture):
                    # never re-execute; resend the original reply.
                    # If the original is still being handled on
                    # another thread, wait briefly for its reply to
                    # land in the cache.
                    if msg.get("tid") is not None:
                        self._pool_submit(self._resend_cached, conn,
                                          ins, seq)
                    return

            # handler execution moves OFF the reader thread (the
            # reference's DispatchQueue + fast-dispatch workers,
            # src/msg/DispatchQueue.h): one connection can have many
            # ops in flight — without this, a primary fanning a write
            # out to replicas serializes every other op sharing the
            # connection behind the fan-out's round trips.  Sequenced
            # frames of ORDERED types additionally keep per-session
            # FIFO through a serial lane feeding the pool (below):
            # the quorum layer relies on mon_commit(v) finishing
            # before mon_accept(v+1) starts, and two pool workers
            # racing frames from one peer broke that (spurious
            # non-contiguous nacks → leader abdication churn).
            # Everything else stays fully parallel; per-object order
            # there is owned by PG locks + versions, as in the
            # reference's sharded op queues.
            control = type_ in self._control
            if ins is not None and type_ in self._ordered:
                with self._in_lock:
                    ins.fifo.append((conn, msg, seq, nbytes, t_rx,
                                     seg))
                    owned = None  # the fifo entry holds it now
                    drain = not ins.draining
                    if drain:
                        ins.draining = True
                if drain and not self._pool_submit(
                        self._drain_session, ins, control=control):
                    self._flush_fifo(ins)  # shutdown: nothing will
                    # drain the lane — release its queued segments
            else:
                if self._pool_submit(self._handle, conn, msg, ins,
                                     seq, nbytes, t_rx, seg,
                                     control=control):
                    owned = None  # the pool task releases it
        finally:
            if owned is not None:
                owned.release()

    def _flush_fifo(self, ins: _InSession) -> None:
        """Drop a session's queued frames (pool refused the lane
        worker at shutdown), releasing their pooled segments."""
        with self._in_lock:
            entries = list(ins.fifo)
            ins.fifo.clear()
            ins.draining = False
        for *_rest, seg in entries:
            if seg is not None:
                seg.release()

    def _drain_session(self, ins: _InSession) -> None:
        """Serial lane worker: run one session's queued frames in
        arrival order, then retire.  At most one lane worker per
        session exists (the ``draining`` flag, flipped under
        _in_lock), so frames never reorder within a session."""
        while True:
            with self._in_lock:
                if not ins.fifo:
                    ins.draining = False
                    return
                conn, msg, seq, nbytes, t_rx, seg = ins.fifo.popleft()
            try:
                self._handle(conn, msg, ins, seq, nbytes, t_rx, seg)
            except Exception as e:
                # the lane must survive a poisoned op, or every later
                # frame from this session queues forever
                self.log.derr(f"{self.name}: handler for "
                              f"{msg.get('type')!r} died: {e!r}")

    def _resend_cached(self, conn, ins: _InSession, seq: int) -> None:
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._in_lock:
                cached = ins.replies.get(seq)
            if cached is not None:
                try:
                    self._send(conn, cached)
                except OSError:
                    pass
                return
            time.sleep(0.02)  # fault-ok: bounded 2s poll of the
            # local duplicate-reply cache, not peer retry pacing

    def _pool_submit(self, fn, *args, control: bool = False) -> bool:
        with self._pool_lock:
            if control:
                pool = self._ctl_pool
                if pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    pool = self._ctl_pool = ThreadPoolExecutor(
                        max_workers=4,
                        thread_name_prefix=f"msgr-ctl:{self.name}")
            else:
                pool = self._pool
                if pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    pool = self._pool = ThreadPoolExecutor(
                        max_workers=16,
                        thread_name_prefix=f"msgr-dispatch:{self.name}")
        try:
            pool.submit(fn, *args)
            return True
        except RuntimeError:
            return False  # shutting down

    def _handle(self, conn: socket.socket, msg: Dict,
                ins: Optional[_InSession], seq, nbytes: int,
                t_rx: Optional[float] = None, seg=None) -> None:
        """``seg`` (when set) is the pooled segment the frame's blob
        views live in — held for the handler's whole execution (a
        handler forwarding a view in a fan-out call blocks until the
        peers reply, so the view stays valid), released on exit."""
        try:
            self._handle_inner(conn, msg, ins, seq, nbytes, t_rx)
        finally:
            if seg is not None:
                seg.release()

    def _handle_inner(self, conn: socket.socket, msg: Dict,
                      ins: Optional[_InSession], seq, nbytes: int,
                      t_rx: Optional[float] = None) -> None:
        type_ = msg.get("type", "")
        ctl = type_ in self._control
        throttle = self.throttles.get(type_)
        if throttle is not None:
            if nbytes > throttle.max:
                # an unsatisfiable get() would wedge this reader thread
                # forever; oversized messages are a protocol error
                self._reply(conn, msg, {"error": "message too large"})
                return
            throttle.get(nbytes)
        try:
            if faults._ACTIVE and faults.partitioned(
                    str(msg.get("frm") or ""), self.name):
                # a directional net.partition covers this sender->
                # receiver pair: the frame never "arrived" — no
                # handler, no reply, no ack; the sender sees the
                # same silence a cut link leaves (its session
                # replays on reconnect, as across a real partition)
                return
            handler = self._handlers.get(type_)
            if handler is None:
                reply = {"error": f"no handler for {type_!r}"}
            else:
                # child span of the sender's call/send span when the
                # frame carries trace context (the server half of the
                # rpc); the no-op span otherwise, so untraced traffic
                # never fills the ring
                with self.tracer.start_span(
                        f"handle:{type_}",
                        child_of=msg.get("trace"),
                        require_parent=True,
                        tags={"frm": msg.get("frm", "")}) as sp:
                    if t_rx is not None:
                        # frame receipt -> handler start: the dispatch
                        # queue wait, split into its own attribution
                        # stage (common/attribution.py) AND the
                        # per-lane wait histogram (the DispatchQueue
                        # saturation signal dump_messenger reads)
                        q_wait = time.monotonic() - t_rx
                        sp.set_tag("q_wait", round(q_wait, 6))
                        cs = self._conn_stat(conn)
                        if ctl:
                            self.pc.hist_add("dispatch_wait_ctl",
                                             q_wait)
                            cs.wait_ctl_s += q_wait
                            cs.wait_ctl_n += 1
                        else:
                            self.pc.hist_add("dispatch_wait_data",
                                             q_wait)
                            cs.wait_data_s += q_wait
                            cs.wait_data_n += 1
                    # watchdog-visible: a handler wedged on a lock or a
                    # peer RPC shows up in dump_blocked with its stack.
                    # Control-lane handlers additionally run as timed
                    # non-blocking scopes (asyncheck): the control lane
                    # is the future event loop's inline lane, so a
                    # handler overrunning asyncheck_loop_budget_ms is
                    # recorded with both-end stack witnesses
                    with watchdog.section(f"{self.name}:{type_}"), (
                            asyncheck.scope(
                                f"handler:{self.name}:{type_}")
                            if ctl else _NULL_CTX):
                        if ctl and faults._ACTIVE:
                            # the --loop-stall drill's armed delay
                            # fires INSIDE the scope, so the runtime
                            # enforcer must name this exact callback
                            faults.sleep_if("msgr.stall_dispatch",
                                            self.name, 0.2)
                        try:
                            reply = handler(msg)
                        except faults.InjectedKill as e:
                            # a fired kill point: the daemon "died"
                            # holding this op — no reply, no ack; the
                            # sender times out and retries, exactly
                            # the crash image a real kill -9 leaves
                            sp.set_tag("error", repr(e))
                            return
                        except Exception as e:
                            sp.set_tag("error", repr(e))
                            reply = {"error": str(e)}
        finally:
            if throttle is not None:
                throttle.put(nbytes)

        frame = None
        if msg.get("tid") is not None:
            frame = {"type": "__reply__", "tid": msg["tid"],
                     "payload": reply}
            try:
                self._send(conn, frame)
            except OSError:
                pass
        if ins is not None:
            if frame is not None:
                # the cache outlives this frame's pooled segment: a
                # reply whose payload references request views must
                # stabilize them before a retransmission seconds
                # from now resends it (booked deliberate copy)
                frame = _materialize_views(frame, self._copy_pc,
                                           "send")
                with self._in_lock:
                    ins.cache_reply(seq, frame)
            else:
                # ack so the sender can trim its unacked buffer —
                # only for fire-and-forget frames: a reply IS the
                # receipt proof for call-type frames (the sender
                # completes that seq on it), so the separate ack
                # frame was pure per-op overhead
                try:
                    self._send(conn, {"type": "__ack__",
                                      "sess": msg.get("_sess"),
                                      "in_seq": seq,
                                      "addr": list(self.addr)})
                except OSError:
                    pass
        if t_rx is not None:
            dt = time.monotonic() - t_rx
            self.pc.hist_add("dispatch_lat", dt)
            self.pc.tinc("dispatch_time", dt)
            cs = self._conn_stat(conn)
            if ctl:
                self.pc.hist_add("dispatch_lat_ctl", dt)
                cs.lat_ctl_s += dt
                cs.lat_ctl_n += 1
            else:
                self.pc.hist_add("dispatch_lat_data", dt)
                cs.lat_data_s += dt
                cs.lat_data_n += 1

    # -- the saturation surface (dump_messenger) -----------------------
    def dump_messenger(self) -> Dict:
        """Per-connection send/dispatch saturation books, worst
        stall first — the `ceph daemon ... dump_messenger` payload.
        Live queue depth/bytes come from the socket's writer queue at
        dump time; the cumulative books from _ConnStats."""
        conns = []
        for cid, cs in list(self._conn_stats.items()):
            entry = cs.dump()
            w = _sock_writers.get(cid)
            q = list(w.q) if w is not None else []
            entry["queue_depth"] = len(q)
            entry["queue_bytes"] = sum(len(o.buf) for o in q)
            conns.append(entry)
        conns.sort(key=lambda c: (c["send_stall_s"],
                                  c["queue_bytes"],
                                  c["bytes_out"]), reverse=True)
        dump = self.pc.dump()
        return {
            "name": self.name,
            "addr": list(self.addr),
            "num_connections": len(conns),
            "connections": conns,
            "totals": {
                "send_stall_s": round(
                    float(dump.get("send_stall_time", 0.0)), 6),
                "send_stalls": int(dump.get("send_stalls", 0)),
                "bytes_in": int(dump.get("bytes_in", 0)),
                "bytes_out": int(dump.get("bytes_out", 0)),
                "frames_in": int(dump.get("frames_in", 0)),
                "frames_out": int(dump.get("frames_out", 0)),
            },
        }

    def wire(self, admin_socket) -> None:
        """Admin-socket surface: dump_messenger beside the daemon's
        optracker/tracer dumps."""
        admin_socket.register(
            "dump_messenger",
            lambda _a: self.dump_messenger(),
            "per-connection send-stall / dispatch-wait books")

    def _reply(self, conn, msg: Dict, payload: Dict) -> None:
        if msg.get("tid") is not None:
            try:
                self._send(conn, {"type": "__reply__",
                                  "tid": msg["tid"],
                                  "payload": payload})
            except OSError:
                pass

    # -- client side ---------------------------------------------------
    def _connect(self, addr: Addr) -> socket.socket:
        addr = tuple(addr)
        with self._conn_lock:
            if self._shut:
                # a background resync racing shutdown() must not dial
                # a fresh connection: it lands AFTER the conn table is
                # cleared, nothing ever closes it, and its reader
                # thread leaks into the next test/runtime
                raise OSError(f"{self.name}: messenger shut down")
            sock = self._conns.get(addr)
            if sock is not None:
                return sock
            sock = socket.create_connection(addr, timeout=5)
            sock.setsockopt(socket.IPPROTO_TCP,
                            socket.TCP_NODELAY, 1)
            self._conns[addr] = sock
            threading.Thread(target=self._reader, args=(sock, addr),
                             daemon=True,
                             name=f"msgr-rd:{self.name}").start()
            return sock

    @staticmethod
    def _hard_close(sock: socket.socket) -> None:
        """shutdown(2) then close: a plain close() is DEFERRED by
        CPython while another thread sits in recv() on the same socket
        object (_io_refs), so the reader would stay blocked on an fd
        nobody can close anymore; SHUT_RDWR tears the connection down
        regardless and wakes the reader with EOF."""
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        # the reader's exit also reaps, but accept-side sockets whose
        # reader never started (shutdown mid-accept) come through here
        # too — reap alongside the _conns cleanup, always
        _reap_writer(sock)

    def _drop(self, addr: Addr) -> None:
        with self._conn_lock:
            sock = self._conns.pop(tuple(addr), None)
        if sock is not None:
            self._hard_close(sock)

    def _session(self, addr: Addr) -> _OutSession:
        addr = tuple(addr)
        sess = self._out.get(addr)
        if sess is None:
            sess = self._out.setdefault(addr, _OutSession())
        return sess

    def _raw_call(self, addr: Addr, msg: Dict,
                  timeout: float = 5.0) -> Dict:
        """tid-correlated exchange below the session layer (the
        handshake itself must not be sequenced)."""
        tid = _next_tid()
        msg = dict(msg, tid=tid, frm=self.name)
        deadline = time.monotonic() + timeout
        ev = threading.Event()
        with self._pending_lock:
            self._waiters[tid] = ev
        sock = None
        try:
            sock = self._connect(addr)
            self._bind_waiter(sock, tid)
            self._send(sock, msg)
            if not ev.wait(max(0.0, deadline - time.monotonic())):
                raise TimeoutError(
                    f"{self.name}: no hello reply from {addr}")
            with self._pending_lock:
                rep = self._pending.pop(tid)
            if isinstance(rep, dict) and \
                    "__session_dead__" in rep:  # wire-ok: local pending-table marker, never framed
                raise OSError(f"{self.name}: {addr} "
                              f"{rep['__session_dead__']}")
            return rep
        finally:
            if sock is not None:
                self._unbind_waiter(sock, tid)
            with self._pending_lock:
                self._waiters.pop(tid, None)
                self._pending.pop(tid, None)

    def _bind_waiter(self, sock, tid: str) -> None:
        with self._conn_lock:
            self._conn_waiters.setdefault(id(sock), set()).add(tid)

    def _unbind_waiter(self, sock, tid: str) -> None:
        with self._conn_lock:
            tids = self._conn_waiters.get(id(sock))
            if tids is not None:
                tids.discard(tid)
                if not tids:
                    del self._conn_waiters[id(sock)]

    def _ensure_synced(self, addr: Addr,
                       deadline: Optional[float] = None) -> None:
        """Under the session lock: connect, handshake, replay the
        unacked tail past the peer's in_seq (ProtocolV2 reconnect).
        Replays every buffered frame, so callers must NOT also send
        frames buffered before this ran.  The handshake honors the
        caller's ``deadline``: connect() can succeed into a dying
        peer's accept backlog and then never see a reply, and a
        5-second wait there — under the session lock — once starved a
        leader's lease round long enough to collapse the quorum."""
        sess = self._session(addr)
        sock = self._connect(addr)
        if sess.synced:
            return
        timeout = 5.0 if deadline is None else \
            max(0.05, min(5.0, deadline - time.monotonic()))
        rep = self._raw_call(addr, {"type": "__hello__",
                                    "sess": self.session_id},
                             timeout=timeout)
        peer_in = int(rep.get("in_seq", 0))
        sess.trim(peer_in)
        for frame in sess.pending():
            self._send(sock, frame)
        sess.synced = True

    def _send_sequenced(self, addr: Addr, msg: Dict,
                        timeout: float = 5.0) -> int:
        """Returns the assigned seq (call() completes it on reply).

        Bounded end to end by ``timeout``: the session lock may be
        held for seconds by a background resync handshaking with a
        dead peer, and a caller with its own small deadline (a lease
        round, a heartbeat) must fail fast rather than queue behind
        it — the quorum-collapse class the lockdep/watchdog layer
        exists to catch."""
        sess = self._session(addr)
        deadline = time.monotonic() + timeout
        if not sess.lock.acquire(timeout=timeout):
            raise TimeoutError(f"{self.name}: session to {addr} busy "
                               f"(resync in progress)")
        try:
            sess.out_seq += 1
            seq = sess.out_seq
            needs_reply = msg.get("tid") is not None
            frame = dict(msg, _s=seq, _sess=self.session_id,
                         frm=self.name)
            if not needs_reply:
                # a fire-and-forget frame sits in the unacked buffer
                # past the caller's return, and a reconnect replays
                # it — any view it carries must be stabilized before
                # the caller's segment recycles (booked deliberate
                # copy).  Call frames skip this: the caller blocks
                # until the seq completes, keeping its views valid.
                frame = _materialize_views(frame, self._copy_pc,
                                           "send")
            sess.buffer(seq, frame, needs_reply)
            try:
                if sess.synced:
                    self._send(self._connect(addr), frame)
                else:
                    self._ensure_synced(addr, deadline)  # replays
                    # every buffered frame, this one included
            except (OSError, TimeoutError):
                # one immediate retry on a fresh connection; further
                # healing happens in the background resync
                self._drop(addr)
                sess.synced = False
                try:
                    self._ensure_synced(addr, deadline)
                except (OSError, TimeoutError):
                    if msg.get("tid") is not None:
                        # the call is failing to its caller: a frame
                        # left buffered would replay a dead op after
                        # the peer returns (e.g. a stale pg_temp_set)
                        sess.complete(seq)
                    raise
            return seq
        finally:
            sess.lock.release()

    def send(self, addr: Addr, msg: Dict) -> None:
        """Fire-and-forget.  Lossless: sequenced + replayed across
        reconnects.  Lossy: one silent reconnect attempt.  When an op
        is being traced on this thread the frame carries the span
        context (no-op span — and no wire field — otherwise)."""
        with self.tracer.start_span(
                f"send:{msg.get('type', '?')}", require_parent=True,
                tags={"peer": f"{addr[0]}:{addr[1]}"}) as sp:
            carrier = self.tracer.inject(sp)
            if carrier is not None:
                msg = dict(msg, trace=carrier)
            if self.lossless:
                try:
                    # bounded: a fire-and-forget caller (heartbeat
                    # loop, map pusher) must not wedge behind a dead
                    # session's resync; the unacked buffer owns
                    # delivery anyway
                    self._send_sequenced(addr, msg, timeout=2.0)
                except (OSError, TimeoutError):
                    pass  # unacked buffer + resync own the retry
                return
            for _ in range(2):
                try:
                    self._send(self._connect(addr), msg)
                    return
                except OSError:
                    self._drop(addr)

    def call(self, addr: Addr, msg: Dict,
             timeout: float = 10.0) -> Dict:
        """Request/response correlated by tid.  On a lossless
        messenger the request is sequenced: if the connection drops
        after the peer processed it, the retransmission is deduped and
        the cached reply resent — exactly-once execution.

        Tracing: every call gets a span (a child of this thread's
        active span when one exists, else a new root) and the frame
        carries its context, so the peer's handler span joins the
        same trace."""
        with self.tracer.start_span(
                f"call:{msg.get('type', '?')}",
                tags={"peer": f"{addr[0]}:{addr[1]}"}) as sp:
            carrier = self.tracer.inject(sp)
            if carrier is not None:
                msg = dict(msg, trace=carrier)
            return self._call(addr, msg, timeout)

    def _call(self, addr: Addr, msg: Dict,
              timeout: float = 10.0) -> Dict:
        tid = _next_tid()
        deadline = time.monotonic() + timeout
        seq = None
        sock = None
        sess = self._session(addr) if self.lossless else None
        ev = threading.Event()
        with self._pending_lock:
            self._waiters[tid] = ev
        try:
            if self.lossless:
                with sess.buf_lock:
                    sess.waiters.add(tid)
                seq = self._send_sequenced(addr, dict(msg, tid=tid),
                                           timeout=timeout)
            else:
                smsg = dict(msg, tid=tid, frm=self.name)
                try:
                    sock = self._connect(addr)
                    self._send(sock, smsg)
                except OSError:
                    # stale cached connection (peer restarted): one
                    # fresh reconnect before giving up
                    self._drop(addr)
                    sock = self._connect(addr)
                    self._send(sock, smsg)
                # lossy: no replay behind this call — it dies with
                # its connection instead of waiting out the timeout
                self._bind_waiter(sock, tid)
            if not ev.wait(max(0.0, deadline - time.monotonic())):
                raise TimeoutError(
                    f"{self.name}: no reply from {addr} "
                    f"for {msg['type']}")
            with self._pending_lock:
                rep = self._pending.pop(tid)
            if isinstance(rep, dict) and \
                    "__session_dead__" in rep:  # wire-ok: local pending-table marker, never framed
                # resync gave the peer up: fail now, not at timeout
                raise OSError(f"{self.name}: {addr} "
                              f"{rep['__session_dead__']}")
            return rep
        except OSError:
            self._drop(addr)
            raise
        finally:
            if seq is not None:
                # replied, timed out, or failed: either way this call
                # is over — stop replaying its request
                self._session(addr).complete(seq)
            if sess is not None:
                with sess.buf_lock:
                    sess.waiters.discard(tid)
            if sock is not None:
                self._unbind_waiter(sock, tid)
            with self._pending_lock:
                self._waiters.pop(tid, None)
                self._pending.pop(tid, None)

    def shutdown(self) -> None:
        self._shut = True
        self._running = False
        with self._pool_lock:
            pools = (self._pool, self._ctl_pool)
            self._pool = self._ctl_pool = None
        for pool in pools:
            if pool is not None:
                pool.shutdown(wait=False)
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_lock:
            socks = list(self._conns.values()) + list(self._accepted)
            self._conns.clear()
            self._accepted.clear()
        for sock in socks:
            self._hard_close(sock)
        self._conn_stats.clear()
