"""Auth — the cephx seam: pre-shared keyring + derived session tickets.

The role of src/auth (CephX): daemons and clients hold a keyring
distributed out of band (the /etc/ceph keyring model); the monitor
issues time-limited session tickets whose keys are DERIVED from the
cluster key (HMAC(cluster_key, name || expiry)), so any keyring holder
verifies a ticket statelessly; messages are authenticated with an HMAC
over the frame (the ProtocolV2 "secure"-mode integrity property).

Wire shape: an authenticated frame carries ``mac`` =
HMAC-SHA256(key, canonical-json(frame minus mac)).  The messenger
signs every outgoing frame and drops inbound frames whose mac is
missing or wrong when a keyring is configured.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import time
from typing import Dict, Optional

from ..common import encoding

# wire-form versions for the persisted/transported auth structures
# (wirecheck registry entries msg.auth.keyring / msg.auth.ticket)
KEYRING_V = 1
TICKET_V = 1


def encode_ticket(ticket: Dict) -> str:
    """Session tickets travel and persist through the versioned
    envelope: a future ticket format (caps, audiences) must be
    refusable by old readers, not silently misverified."""
    return encoding.encode(dict(ticket), TICKET_V, 1)


def decode_ticket(blob) -> Dict:
    """Lenient: pre-envelope raw-dict tickets (writer v0) still
    decode."""
    v, data = encoding.decode_any(blob, supported=TICKET_V,
                                  struct="msg.auth.ticket")
    if not isinstance(data, dict):
        raise encoding.MalformedInput(
            f"msg.auth.ticket v{v}: payload is not an object")
    return data


class Keyring:
    def __init__(self, key: bytes):
        self.key = key

    @classmethod
    def generate(cls) -> "Keyring":
        return cls(os.urandom(32))

    @classmethod
    def from_hex(cls, s: str) -> "Keyring":
        return cls(bytes.fromhex(s))

    def to_hex(self) -> str:
        return self.key.hex()

    # -- versioned keyring file form (the /etc/ceph keyring role) -----
    def to_wire(self) -> str:
        return encoding.encode({"key": self.key.hex()}, KEYRING_V, 1)

    @classmethod
    def from_wire(cls, blob) -> "Keyring":
        v, data = encoding.decode(blob, supported=KEYRING_V,
                                  struct="msg.auth.keyring")
        try:
            return cls(bytes.fromhex(data["key"]))
        except (KeyError, TypeError, ValueError) as e:
            raise encoding.MalformedInput(
                f"msg.auth.keyring v{v}: bad payload: {e!r}")

    # -- frame authentication -----------------------------------------
    @staticmethod
    def _canonical(msg: Dict, blobs=None) -> bytes:
        body = {k: v for k, v in msg.items() if k != "mac"}
        out = json.dumps(body, sort_keys=True,  # wire-ok: MAC canonical form, never decoded
                         separators=(",", ":")).encode()
        # data segments are covered by their digests, so a tampered
        # raw attachment breaks the frame MAC exactly like a tampered
        # control field
        for b in (blobs or ()):
            out += hashlib.sha256(b).digest()
        return out

    def sign(self, msg: Dict, blobs=None) -> str:
        return hmac.new(self.key, self._canonical(msg, blobs),
                        hashlib.sha256).hexdigest()

    def verify(self, msg: Dict, blobs=None) -> bool:
        mac = msg.get("mac")
        if not isinstance(mac, str):
            return False
        return hmac.compare_digest(mac, self.sign(msg, blobs))

    # -- session tickets (CephX ticket flow) --------------------------
    def issue_ticket(self, name: str, lifetime: float = 3600.0,
                     now: Optional[float] = None) -> Dict:
        """``now`` pins the clock (corpus generation, tests);
        defaults to wall time."""
        expires = (time.time() if now is None else now) + lifetime
        seed = f"{name}:{expires:.3f}".encode()
        session = hmac.new(self.key, seed, hashlib.sha256).hexdigest()
        return {"name": name, "expires": round(expires, 3),
                "session_key": session}

    def verify_ticket(self, ticket: Dict) -> bool:
        try:
            if float(ticket["expires"]) < time.time():
                return False
            seed = (f"{ticket['name']}:"
                    f"{float(ticket['expires']):.3f}").encode()
            want = hmac.new(self.key, seed,
                            hashlib.sha256).hexdigest()
            return hmac.compare_digest(want, ticket["session_key"])
        except (KeyError, TypeError, ValueError):
            return False
