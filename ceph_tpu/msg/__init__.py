"""Cluster fabric — the reference's src/msg surface, re-scoped.

The reference's AsyncMessenger carries BOTH bulk data and control
traffic over TCP (ProtocolV2, epoll workers).  TPU-native, the bulk
data plane is XLA collectives over ICI/DCN inside compiled programs
(``ceph_tpu.parallel``) — so what remains host-side is the control
plane: map epochs, heartbeats, shard fetch/push for recovery.
``messenger.Messenger`` is that plane: a threaded TCP transport with
length-prefixed JSON messages, typed dispatch, and reconnecting
send — the Messenger/Dispatcher seam (src/msg/Messenger.h,
Dispatcher.h) sized to its remaining job.
"""
